"""GF(2^8) arithmetic and the bit-linear lifting used by the TPU codec.

The field is GF(2^8) with the standard Reed-Solomon reduction
polynomial x^8+x^4+x^3+x^2+1 (0x11d) and generator alpha=2 — the same
field the reference's codec dependency uses (klauspost/reedsolomon,
reference go.mod:10).

Two representations live here:

1. Classic exp/log tables for scalar/numpy CPU math.
2. The *bit-matrix lifting*: multiplication by a constant c is a
   GF(2)-linear map on the 8 bits of the operand, y_bits = M_c @ x_bits
   (mod 2).  Lifting every entry of a GF matrix A (m x k) to its 8x8
   bit-matrix yields a (8m x 8k) 0/1 matrix G with
   (A (*) X)_bits = G @ X_bits (mod 2) — which turns the whole RS
   encode/decode into ONE dense matmul that the TPU MXU executes in
   bf16 with exact f32 accumulation (sums of 0/1 terms stay well under
   2^24).  This is the TPU-native analogue of the AVX2 nibble-table
   trick in the reference's dependency.
"""

from __future__ import annotations

import functools

import numpy as np

_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1

# ---------------------------------------------------------------------------
# Table construction (module-load time; a few microseconds)
# ---------------------------------------------------------------------------


def _build_tables():
    exp = np.zeros(512, dtype=np.uint8)  # doubled to skip the mod-255
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[0:255]
    return exp, log


GF_EXP, GF_LOG = _build_tables()

# Full 256x256 multiplication table: 64 KiB, used by the numpy CPU codec.
_a = np.arange(256)
_la = GF_LOG[_a][:, None] + GF_LOG[_a][None, :]
GF_MUL_TABLE = GF_EXP[_la].astype(np.uint8)
GF_MUL_TABLE[0, :] = 0
GF_MUL_TABLE[:, 0] = 0


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[GF_LOG[a] + GF_LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(GF_EXP[255 - GF_LOG[a]])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


# ---------------------------------------------------------------------------
# Matrix math over GF(2^8) (numpy, host-side; all matrices are tiny:
# at N=128 the largest is 84x44)
# ---------------------------------------------------------------------------


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(m,k) x (k,n) matrix product over GF(2^8)."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    out = np.zeros((m, n), dtype=np.uint8)
    for i in range(k):
        out ^= GF_MUL_TABLE[a[:, i]][:, b[i, :]]
    return out


def gf_mat_inv(a: np.ndarray) -> np.ndarray:
    """Invert a (k,k) GF(2^8) matrix by Gauss-Jordan elimination.

    Used per-decode to build the reconstruction matrix from the
    surviving shard rows (reference rbc/rbc.go:88-90 `interpolate`);
    O(k^3) table lookups on host — microseconds at k<=64.
    """
    k = a.shape[0]
    aug = np.concatenate([a.astype(np.uint8), np.eye(k, dtype=np.uint8)], axis=1)
    for col in range(k):
        pivot = None
        for row in range(col, k):
            if aug[row, col]:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf_inv(int(aug[col, col]))
        aug[col] = GF_MUL_TABLE[inv_p][aug[col]]
        factors = aug[:, col].copy()
        factors[col] = 0
        nz = np.nonzero(factors)[0]
        if nz.size:
            # aug[r] ^= factors[r] * aug[col] for every row with a
            # nonzero entry in this column, vectorized via the table.
            aug[nz] ^= GF_MUL_TABLE[factors[nz]][:, aug[col]]
    return aug[:, k:]


def systematic_rs_matrix(n: int, k: int) -> np.ndarray:
    """Build the (n,k) systematic RS generator matrix.

    Vandermonde V[i,j] = x_i^j with distinct points x_i = i, normalised
    so the top k rows are the identity: A = V @ inv(V[:k]).  Any k rows
    of A are invertible, so any k of the n shards reconstruct the data
    (docs/RBC-EN.md:17, "even if a maximum of k data is lost").
    """
    assert 1 <= k <= n <= 256
    v = np.zeros((n, k), dtype=np.uint8)
    for i in range(n):
        for j in range(k):
            v[i, j] = gf_pow(i, j)
    a = gf_matmul(v, gf_mat_inv(v[:k]))
    assert np.array_equal(a[:k], np.eye(k, dtype=np.uint8))
    return a


# ---------------------------------------------------------------------------
# Bit-matrix lifting
# ---------------------------------------------------------------------------


@functools.cache
def _bitmat_table() -> np.ndarray:
    """(256, 8, 8) uint8: BITMAT[c] is M_c with y_bits = M_c @ x_bits.

    Column j of M_c holds the bits (LSB-first) of c * x^j, i.e. of
    gf_mul(c, 1 << j).
    """
    t = np.zeros((256, 8, 8), dtype=np.uint8)
    for c in range(256):
        for j in range(8):
            prod = gf_mul(c, 1 << j)
            for r in range(8):
                t[c, r, j] = (prod >> r) & 1
    return t


def lift_to_bits(a: np.ndarray) -> np.ndarray:
    """Lift a GF(2^8) matrix (m,k) to its (8m, 8k) 0/1 bit-matrix G.

    G[i*8+r, j*8+c] = M_{a[i,j]}[r, c]; then for byte matrices X,
    bits(A (*) X) = G @ bits(X) mod 2.
    """
    m, k = a.shape
    g = _bitmat_table()[a]  # (m, k, 8, 8)
    return g.transpose(0, 2, 1, 3).reshape(8 * m, 8 * k)


def bytes_to_bits(x: np.ndarray) -> np.ndarray:
    """(r, l) uint8 -> (8r, l) uint8 bit-planes, LSB-first per byte."""
    r, l = x.shape
    bits = ((x[:, None, :] >> np.arange(8, dtype=np.uint8)[None, :, None]) & 1)
    return bits.reshape(8 * r, l)


def bits_to_bytes(bits: np.ndarray) -> np.ndarray:
    """(8r, l) 0/1 -> (r, l) uint8, inverse of bytes_to_bits."""
    r8, l = bits.shape
    b = bits.reshape(r8 // 8, 8, l).astype(np.uint32)
    weights = (1 << np.arange(8, dtype=np.uint32))[None, :, None]
    return (b * weights).sum(axis=1).astype(np.uint8)
