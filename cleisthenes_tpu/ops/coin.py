"""Threshold common coin for BBA.

The reference specifies (but does not implement) a network-global
random bit per BBA round, "built in such a way that the correct
processes need to cooperate to compute the value of each bit"
(reference docs/BBA-EN.md:163-177) — i.e. a threshold-cryptographic
coin, costed at ~4N^2 signature sharings per node per epoch
(docs/HONEYBADGER-EN.md:93-94).

Construction: a DDH-based threshold VUF over the same group as TPKE.
For coin id C, let x = hash_to_group(C) (unknown discrete log).  Each
node publishes share d_i = x^{s_i} with a Chaum-Pedersen proof; any
f+1 verified shares Lagrange-combine to the unique value x^s, and the
coin bit is a hash of it.  Unpredictable until f+1 nodes cooperate,
and identical at every correct node — exactly the two properties
docs/BBA-EN.md:174-177 demands.  Share verification batches across
shares (and across concurrent BBA instances) in one TPU dispatch via
ops/modmath.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

from cleisthenes_tpu.ops import tpke
from cleisthenes_tpu.ops.modmath import DEFAULT_GROUP, GroupParams
from cleisthenes_tpu.ops.tpke import (
    DhShare,
    ThresholdPublicKey,
    ThresholdSecretShare,
)


def coin_base(
    coin_id: bytes, group: GroupParams = DEFAULT_GROUP
) -> int:
    """The group element x = H2G(coin_id) whose s-th power is the coin."""
    return tpke.hash_to_group(b"coin|" + coin_id, group)


class CommonCoin:
    """One coin key set shared by all BBA instances of a network."""

    def __init__(
        self, pub: ThresholdPublicKey, backend: str = "cpu", mesh=None
    ):
        self.pub = pub
        self.backend = backend
        self.mesh = mesh
        self.group = pub.group  # the key set carries its group

    def share(
        self, secret: ThresholdSecretShare, coin_id: bytes
    ) -> DhShare:
        return tpke.issue_share(
            secret,
            coin_base(coin_id, self.group),
            b"coin|" + coin_id,
            self.group,
        )

    def verify_shares(
        self, coin_id: bytes, shares: Sequence[DhShare]
    ) -> List[bool]:
        return tpke.verify_shares(
            self.pub,
            coin_base(coin_id, self.group),
            shares,
            b"coin|" + coin_id,
            self.backend,
            self.mesh,
        )

    def group_params(self, coin_id: bytes):
        """(pub, base, context) for this coin — the key the protocol
        hub uses to fold coin-share verification into one cross-
        instance tpke.verify_share_groups dispatch."""
        return self.pub, coin_base(coin_id, self.group), b"coin|" + coin_id

    def combine(self, coin_id: bytes, shares: Sequence[DhShare]) -> int:
        """Full 256-bit coin value from >= f+1 verified shares."""
        val = tpke.combine_shares(shares, self.pub.threshold, self.group)
        return int.from_bytes(
            hashlib.sha256(
                b"coinval|"
                + coin_id
                + val.to_bytes(self.group.nbytes, "big")
            ).digest(),
            "big",
        )

    def toss(self, coin_id: bytes, shares: Sequence[DhShare]) -> bool:
        """The single random bit BBA phase 3 consumes
        (docs/BBA-EN.md:163-181)."""
        return bool(self.combine(coin_id, shares) & 1)


__all__ = ["CommonCoin", "coin_base"]
