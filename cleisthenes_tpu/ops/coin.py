"""Threshold common coin for BBA.

The reference specifies (but does not implement) a network-global
random bit per BBA round, "built in such a way that the correct
processes need to cooperate to compute the value of each bit"
(reference docs/BBA-EN.md:163-177) — i.e. a threshold-cryptographic
coin, costed at ~4N^2 signature sharings per node per epoch
(docs/HONEYBADGER-EN.md:93-94).

Construction: a DDH-based threshold VUF over the same group as TPKE.
For coin id C, let x = hash_to_group(C) (unknown discrete log).  Each
node publishes share d_i = x^{s_i} with a Chaum-Pedersen proof; any
f+1 verified shares Lagrange-combine to the unique value x^s, and the
coin bit is a hash of it.  Unpredictable until f+1 nodes cooperate,
and identical at every correct node — exactly the two properties
docs/BBA-EN.md:174-177 demands.  Share verification batches across
shares (and across concurrent BBA instances) in one TPU dispatch via
ops/modmath.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

from cleisthenes_tpu.ops import tpke
from cleisthenes_tpu.ops.modmath import DEFAULT_GROUP, GroupParams
from cleisthenes_tpu.ops.tpke import (
    DhShare,
    ThresholdPublicKey,
    ThresholdSecretShare,
    issue_shares_batch,
    verify_share_groups,
)


def coin_base(
    coin_id: bytes, group: GroupParams = DEFAULT_GROUP
) -> int:
    """The group element x = H2G(coin_id) whose s-th power is the coin."""
    return tpke.hash_to_group(b"coin|" + coin_id, group)


def share_batch(
    items: Sequence[tuple],
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
) -> List[DhShare]:
    """Issue MANY coin shares — across instances, rounds, and (in an
    in-proc cluster) issuers — in ONE vectorized multi-exponentiation
    dispatch with ONE CP-nonce entropy draw (the wave-column treatment
    ``Tpke.dec_share_batch`` already gave the TPKE side; Thetacrypt's
    batched threshold-service shape, PAPERS.md 2502.03247).

    ``items``: sequence of ``(secret, base, context, vk)`` exactly as
    ``tpke.issue_shares_batch`` takes them — ``base``/``context`` come
    from ``CommonCoin.group_params(coin_id)``, ``vk`` is the issuer's
    verification key (None recomputes it in the same dispatch).
    Semantics match mapping ``tpke.issue_share`` over the items;
    result order matches input order.  The CryptoHub's coin-issue
    column (``take_coin_issues``) dispatches through here; the scalar
    comparison arm (``HoneyBadger._drain_coin_issues``) and the
    lockstep spmd plane call ``tpke.issue_shares_batch`` directly —
    the ``coin_share_batches`` counter is the hub's own tally,
    incremented at BOTH the hub dispatch and the scalar drain, not a
    call count of this function."""
    return issue_shares_batch(
        items, group=group, backend=backend, mesh=mesh
    )


class CommonCoin:
    """One coin key set shared by all BBA instances of a network."""

    def __init__(
        self, pub: ThresholdPublicKey, backend: str = "cpu", mesh=None
    ):
        self.pub = pub
        self.backend = backend
        self.mesh = mesh
        self.group = pub.group  # the key set carries its group

    def share(
        self, secret: ThresholdSecretShare, coin_id: bytes
    ) -> DhShare:
        return tpke.issue_share(
            secret,
            coin_base(coin_id, self.group),
            b"coin|" + coin_id,
            self.group,
        )

    def share_batch(
        self,
        secret: ThresholdSecretShare,
        coin_ids: Sequence[bytes],
        vk: Optional[int] = None,
    ) -> List[DhShare]:
        """One issuer's coin shares for MANY coins — every (instance,
        round) a wave touched — in one vectorized dispatch and one
        CP-nonce draw.  Semantically ``[share(secret, cid) for cid in
        coin_ids]``; ``vk`` (the issuer's verification key
        g^{s_i}) defaults to the key set's own, saving one
        exponentiation per item."""
        if not coin_ids:
            return []
        if vk is None:
            vk = self.pub.verification_keys[secret.index - 1]
        return share_batch(
            [
                (secret, coin_base(cid, self.group), b"coin|" + cid, vk)
                for cid in coin_ids
            ],
            group=self.group,
            backend=self.backend,
            mesh=self.mesh,
        )

    def verify_shares(
        self, coin_id: bytes, shares: Sequence[DhShare]
    ) -> List[bool]:
        return tpke.verify_shares(
            self.pub,
            coin_base(coin_id, self.group),
            shares,
            b"coin|" + coin_id,
            self.backend,
            self.mesh,
        )

    def verify_shares_batch(
        self, entries: Sequence[Tuple[bytes, Sequence[DhShare]]]
    ) -> List[List[bool]]:
        """CP-verify MANY coins' pooled shares — across all BBA
        instances and rounds a wave touched — in ONE
        dual-exponentiation dispatch (semantically
        ``[verify_shares(cid, shs) for cid, shs in entries]``; result
        order matches input order).  The protocol hub reaches the same
        dispatch shape by folding coin groups into its share column
        (tpke.verify_share_groups); this is the coin-only entry point
        for callers without a hub (lockstep executor, tests)."""
        if not entries:
            return []
        return verify_share_groups(
            [
                (
                    self.pub,
                    coin_base(cid, self.group),
                    shs,
                    b"coin|" + cid,
                )
                for cid, shs in entries
            ],
            self.backend,
            self.mesh,
        )

    def group_params(self, coin_id: bytes):
        """(pub, base, context) for this coin — the key the protocol
        hub uses to fold coin-share verification into one cross-
        instance tpke.verify_share_groups dispatch."""
        return self.pub, coin_base(coin_id, self.group), b"coin|" + coin_id

    def combine(self, coin_id: bytes, shares: Sequence[DhShare]) -> int:
        """Full 256-bit coin value from >= f+1 verified shares."""
        val = tpke.combine_shares(shares, self.pub.threshold, self.group)
        return int.from_bytes(
            hashlib.sha256(
                b"coinval|"
                + coin_id
                + val.to_bytes(self.group.nbytes, "big")
            ).digest(),
            "big",
        )

    def toss(self, coin_id: bytes, shares: Sequence[DhShare]) -> bool:
        """The single random bit BBA phase 3 consumes
        (docs/BBA-EN.md:163-181)."""
        return bool(self.combine(coin_id, shares) & 1)


__all__ = ["CommonCoin", "coin_base", "share_batch"]
