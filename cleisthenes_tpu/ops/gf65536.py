"""GF(2^16) arithmetic and 16-bit lifting: rosters past 256 validators.

GF(2^8) admits at most 256 distinct shard indices — the hard ceiling
the reference inherits from its codec dependency (klauspost/reedsolomon
caps data+parity at 256 shards; reference go.mod:10), which is why its
lineage cannot run RBC at N=512.  This module is the same construction
one field up: GF(2^16) with the standard reduction polynomial
x^16 + x^12 + x^3 + x + 1 (0x1100B), generator alpha=2, supporting up
to 65536 shard indices.

Representations mirror ops/gf256.py:

1. exp/log tables (512 KiB + 256 KiB) for scalar and vectorized host
   math — the full 2^16 x 2^16 product table would be 4 GiB, so
   vectorized multiplication goes through exp[log a + log b] with
   zero masking instead.
2. The bit-matrix lifting: multiplication by a constant is GF(2)-linear
   on the 16 bits of the operand, so an (m, k) GF(2^16) matrix lifts to
   a (16m, 16k) 0/1 matrix and the whole RS transform becomes one MXU
   matmul over bit-planes — dots sum <= 16k <= 2^24 ones, exact in the
   bf16-multiply/f32-accumulate path (ops/rs16_xla.py).

Symbols are uint16; shard byte rows of even length L view as L/2
symbols little-endian (ops/rs16_cpu.py handles the byte<->symbol view).
"""

from __future__ import annotations

import functools

import numpy as np

_POLY = 0x1100B  # x^16 + x^12 + x^3 + x + 1
ORDER = 1 << 16
E = 16


def _build_tables():
    exp = np.zeros(2 * (ORDER - 1), dtype=np.uint16)
    log = np.zeros(ORDER, dtype=np.int32)
    x = 1
    for i in range(ORDER - 1):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & ORDER:
            x ^= _POLY
    exp[ORDER - 1 :] = exp[: ORDER - 1]
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[GF_LOG[a] + GF_LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(GF_EXP[(ORDER - 1) - GF_LOG[a]])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    # Multiply in Python ints: GF_LOG is int32 and GF_LOG[a] * n wraps
    # silently for n >~ 32768 at this field's index scale.
    return int(GF_EXP[(int(GF_LOG[a]) * n) % (ORDER - 1)])


def gf_mul_vec(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Elementwise product of uint16 arrays (broadcasting ok)."""
    a = np.asarray(a, dtype=np.uint16)
    b = np.asarray(b, dtype=np.uint16)
    out = GF_EXP[GF_LOG[a] + GF_LOG[b]].astype(np.uint16)
    return np.where((a == 0) | (b == 0), np.uint16(0), out)


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(m,k) x (k,n) matrix product over GF(2^16)."""
    m, k = a.shape
    k2, n = b.shape
    assert k == k2
    out = np.zeros((m, n), dtype=np.uint16)
    for i in range(k):
        out ^= gf_mul_vec(a[:, i : i + 1], b[i : i + 1, :])
    return out


def gf_mat_inv(a: np.ndarray) -> np.ndarray:
    """Invert a (k,k) GF(2^16) matrix by Gauss-Jordan elimination
    (same shape of algorithm as gf256.gf_mat_inv)."""
    k = a.shape[0]
    aug = np.concatenate(
        [a.astype(np.uint16), np.eye(k, dtype=np.uint16)], axis=1
    )
    for col in range(k):
        pivot = None
        for row in range(col, k):
            if aug[row, col]:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular GF(2^16) matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf_inv(int(aug[col, col]))
        aug[col] = gf_mul_vec(np.uint16(inv_p), aug[col])
        factors = aug[:, col].copy()
        factors[col] = 0
        nz = np.nonzero(factors)[0]
        if nz.size:
            aug[nz] ^= gf_mul_vec(factors[nz][:, None], aug[col][None, :])
    return aug[:, k:]


def systematic_rs_matrix(n: int, k: int) -> np.ndarray:
    """(n,k) systematic RS generator over GF(2^16): Vandermonde at
    distinct points x_i = i, normalised so the top k rows are the
    identity (any k rows invertible — docs/RBC-EN.md:17)."""
    assert 1 <= k <= n <= ORDER
    i_col = np.arange(n, dtype=np.int64)
    v = np.zeros((n, k), dtype=np.uint16)
    v[:, 0] = 1
    for j in range(1, k):
        v[:, j] = gf_mul_vec(v[:, j - 1], i_col.astype(np.uint16))
    a = gf_matmul(v, gf_mat_inv(v[:k]))
    assert np.array_equal(a[:k], np.eye(k, dtype=np.uint16))
    return a


# ---------------------------------------------------------------------------
# Bit-matrix lifting (the 2^16-entry analogue of gf256._bitmat_table is
# 16 MiB and touched sparsely, so lifting computes per-matrix instead)
# ---------------------------------------------------------------------------


def lift_to_bits(a: np.ndarray) -> np.ndarray:
    """Lift a GF(2^16) matrix (m,k) to its (16m, 16k) 0/1 bit-matrix.

    Column j of the 16x16 block for constant c holds the bits
    (LSB-first) of c * x^j."""
    m, k = a.shape
    # prods[i, j, col] = a[i,j] * 2^col  — vectorized exp/log multiply
    pow2 = (np.uint16(1) << np.arange(E, dtype=np.uint16))
    prods = gf_mul_vec(a[:, :, None], pow2[None, None, :])  # (m,k,16)
    bits = (
        (prods[:, :, None, :] >> np.arange(E, dtype=np.uint16)[None, None, :, None])
        & 1
    ).astype(np.uint8)  # (m, k, 16 rows, 16 cols)
    return bits.transpose(0, 2, 1, 3).reshape(E * m, E * k)


def symbols_to_bits(x: np.ndarray) -> np.ndarray:
    """(r, S) uint16 -> (16r, S) uint8 bit-planes, LSB-first."""
    r, s = x.shape
    bits = (
        (x[:, None, :] >> np.arange(E, dtype=np.uint16)[None, :, None]) & 1
    ).astype(np.uint8)
    return bits.reshape(E * r, s)


def bits_to_symbols(bits: np.ndarray) -> np.ndarray:
    """(16r, S) 0/1 -> (r, S) uint16, inverse of symbols_to_bits."""
    r16, s = bits.shape
    b = bits.reshape(r16 // E, E, s).astype(np.uint32)
    weights = (1 << np.arange(E, dtype=np.uint32))[None, :, None]
    return (b * weights).sum(axis=1).astype(np.uint16)


__all__ = [
    "E",
    "ORDER",
    "GF_EXP",
    "GF_LOG",
    "gf_mul",
    "gf_inv",
    "gf_pow",
    "gf_mul_vec",
    "gf_matmul",
    "gf_mat_inv",
    "systematic_rs_matrix",
    "lift_to_bits",
    "symbols_to_bits",
    "bits_to_symbols",
]
