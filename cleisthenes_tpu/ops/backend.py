"""The BatchCrypto / ErasureCoder seam.

BASELINE.json's north star names this interface: the per-epoch crypto
(RS encode/decode, Merkle proofs, TPKE share ops, coin combine) sits
behind ``BatchCrypto``/``ErasureCoder`` with ``cpu`` and ``tpu``
backends selected by config — the seam that keeps every protocol test
runnable without a TPU.  It mirrors the reference's only pluggable hot
path, the ``reedsolomon.Encoder`` held by RBC (reference rbc/rbc.go:21).
"""

from __future__ import annotations

import abc
from typing import Sequence

import numpy as np


class ErasureCoder(abc.ABC):
    """Systematic (n, k) Reed-Solomon codec.

    Shards are byte matrices: ``data`` is (k, L), full shard sets are
    (n, L) with rows 0..k-1 the data shards and rows k..n-1 parity
    (reference rbc/rbc.go:98-100 `shard`, :88-90 `interpolate`).

    ``MAX_N`` is the field's shard-index ceiling: 256 for the GF(2^8)
    coders (the same hard limit as the reference's codec dependency),
    65536 for the GF(2^16) coders that lift it (ops/gf65536.py).
    """

    MAX_N = 256

    def __init__(self, n: int, k: int):
        if not (1 <= k <= n <= self.MAX_N):
            raise ValueError(
                f"need 1 <= k <= n <= {self.MAX_N}, got n={n} k={k}"
            )
        self.n = n
        self.k = k

    @abc.abstractmethod
    def encode(self, data: np.ndarray) -> np.ndarray:
        """(k, L) data shards -> (n, L) data+parity shards."""

    def _normalize_indices(self, indices: Sequence[int]) -> tuple:
        out = tuple(int(i) for i in indices)
        if len(out) != self.k or len(set(out)) != self.k:
            raise ValueError(
                f"need exactly k={self.k} distinct shard indices, got {out}"
            )
        if not all(0 <= i < self.n for i in out):
            raise ValueError(f"shard indices out of range [0, {self.n}): {out}")
        return out

    def decode(self, indices: Sequence[int], shards: np.ndarray) -> np.ndarray:
        """Reconstruct the (k, L) data shards from any k survivors.

        ``indices``: which of the n shard rows the k given shards are
        (distinct, ascending not required).  ``shards``: (k, L).
        """
        indices = self._normalize_indices(indices)
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        if shards.ndim != 2 or shards.shape[0] != self.k:
            raise ValueError(f"expected (k={self.k}, L) shards, got {shards.shape}")
        if indices == tuple(range(self.k)):
            return shards.copy()
        return self._decode_impl(indices, shards)

    @abc.abstractmethod
    def _decode_impl(self, indices: tuple, shards: np.ndarray) -> np.ndarray:
        """Backend decode after validation; indices are k distinct ints
        and not the identity pattern."""

    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        """(B, k, L) -> (B, n, L); default loops, backends override."""
        return np.stack([self.encode(d) for d in data])

    def decode_batch(
        self, indices: np.ndarray, shards: np.ndarray
    ) -> np.ndarray:
        """(B, k) indices + (B, k, L) shards -> (B, k, L) data."""
        return np.stack(
            [self.decode(list(ix), sh) for ix, sh in zip(indices, shards)]
        )


def make_erasure_coder(
    backend: str, n: int, k: int, mesh=None
) -> ErasureCoder:
    if n > 256:
        # past the GF(2^8) shard-index ceiling (the reference's hard
        # limit): the GF(2^16) coders.  The native C++ kernel is
        # 8-bit-only, so 'cpp' serves these rosters from the host
        # reference path.
        from cleisthenes_tpu.ops.rs16 import (
            Cpu16ErasureCoder,
            Xla16ErasureCoder,
        )

        if backend in ("cpu", "cpp"):
            return Cpu16ErasureCoder(n, k)
        if backend == "tpu":
            return Xla16ErasureCoder(n, k, mesh=mesh)
        raise ValueError(f"unknown erasure backend {backend!r}")
    if backend == "cpu":
        from cleisthenes_tpu.ops.rs_cpu import CpuErasureCoder

        return CpuErasureCoder(n, k)
    if backend == "cpp":
        from cleisthenes_tpu.ops.rs_cpp import CppErasureCoder

        return CppErasureCoder(n, k)
    if backend == "tpu":
        from cleisthenes_tpu.ops.rs_xla import XlaErasureCoder

        return XlaErasureCoder(n, k, mesh=mesh)
    raise ValueError(f"unknown erasure backend {backend!r}")


class BatchCrypto:
    """Bundle of crypto-plane backends for one (n, f) configuration.

    Grows as subsystems land: erasure coding, Merkle forest, TPKE,
    common coin.  ``get_backend(config)`` is the single construction
    point used by the protocol layer.

    ``mesh_shape`` (Config.mesh_shape) shards the whole crypto plane
    over a ('v', 'l') device mesh (parallel.mesh.CryptoMesh): RS
    batches partition over both axes, hash/modexp batches over all
    devices flat.  Only meaningful under the 'tpu' backend — the numpy
    and native backends are single-host by definition.
    """

    def __init__(
        self, backend: str, n: int, f: int, k: int, mesh_shape=None
    ):
        from cleisthenes_tpu.ops.merkle import make_merkle

        self.backend = backend
        self.n = n
        self.f = f
        self.k = k
        # remembered so per-geometry siblings (the hub's resized-
        # roster decode groups) inherit the same device-mesh layout
        self.mesh_shape = None if mesh_shape is None else tuple(mesh_shape)
        self.mesh = None
        if mesh_shape is not None and backend == "tpu":
            from cleisthenes_tpu.parallel.mesh import make_crypto_mesh

            self.mesh = make_crypto_mesh(tuple(mesh_shape))
        self.erasure = make_erasure_coder(backend, n, k, mesh=self.mesh)
        # the native backend accelerates the GF plane; hashing and
        # modexp stay on their cpu reference implementations
        self.merkle = make_merkle(self.engine_backend, mesh=self.mesh)

    @property
    def engine_backend(self) -> str:
        """Backend name for the modexp engine (tpke/coin verify)."""
        return "cpu" if self.backend == "cpp" else self.backend

    def decode_recheck_batch(self, indices, shards):
        """RBC delivery check: decode + re-encode + Merkle roots
        (docs/RBC-EN.md:37-39) for a batch of instances.

        Returns ``(data (B, k, L), roots (B, 32) uint8, dispatches)``.
        The 'tpu' backend fuses the chain into one XLA program when the
        erasure patterns match (the common case); otherwise — and on
        the host backends — it is the 3-step sequence."""
        fused = getattr(self.erasure, "decode_recheck_batch", None)
        if fused is not None:
            out = fused(indices, shards)
            if out is not None:
                data, roots = out
                return data, roots, 1
        data = self.erasure.decode_batch(indices, shards)
        full = self.erasure.encode_batch(data)
        trees = self.merkle.build_batch(full)
        roots = np.stack(
            [np.frombuffer(t.root, dtype=np.uint8) for t in trees]
        )
        return data, roots, 3

    def tpke(self, pub):
        """Threshold-decryption service bound to this backend
        (pub: tpke.ThresholdPublicKey)."""
        from cleisthenes_tpu.ops.tpke import Tpke

        return Tpke(pub, backend=self.engine_backend, mesh=self.mesh)

    def coin(self, pub):
        """Common-coin service bound to this backend."""
        from cleisthenes_tpu.ops.coin import CommonCoin

        return CommonCoin(pub, backend=self.engine_backend, mesh=self.mesh)


def get_backend(config) -> BatchCrypto:
    # k comes from Config.data_shards, the single source of the
    # N - 2f formula (validated there against n >= 3f+1).
    return BatchCrypto(
        config.crypto_backend,
        config.n,
        config.f,
        config.data_shards,
        mesh_shape=config.mesh_shape,
    )
