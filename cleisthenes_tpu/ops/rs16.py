"""Reed-Solomon over GF(2^16): rosters past the 256-shard ceiling.

Same systematic construction and the same two backends as the GF(2^8)
codec (ops/rs_cpu.py, ops/rs_xla.py), one field up: shard byte rows of
even length L are L/2 little-endian uint16 symbols, and the XLA path
lifts the generator to a (16n x 16k) 0/1 matrix so the whole transform
is one MXU matmul over 16 bit-planes (dots sum <= 16k ones — exact in
bf16-multiply/f32-accumulate; ops/gf65536.py module docstring).

The reference's lineage cannot express these rosters at all: its codec
dependency hard-caps data+parity shards at 256 (klauspost/reedsolomon,
reference go.mod:10).  N=512 RBC — 512 distinct shard indices — needs
this field.
"""

from __future__ import annotations

import functools

import numpy as np

from cleisthenes_tpu.ops import gf65536 as gf
from cleisthenes_tpu.ops.backend import ErasureCoder


def _to_symbols(x: np.ndarray) -> np.ndarray:
    """(r, L) uint8, L even -> (r, L/2) uint16 little-endian."""
    x = np.ascontiguousarray(x, dtype=np.uint8)
    if x.shape[-1] % 2:
        raise ValueError(
            f"GF(2^16) shards need even byte length, got L={x.shape[-1]}"
        )
    return x.view("<u2")


def _to_bytes(x: np.ndarray) -> np.ndarray:
    """(r, S) uint16 -> (r, 2S) uint8 little-endian."""
    return np.ascontiguousarray(x, dtype="<u2").view(np.uint8)


class Cpu16ErasureCoder(ErasureCoder):
    """Host reference: exp/log-table matmul over uint16 symbols."""

    MAX_N = gf.ORDER

    def __init__(self, n: int, k: int):
        super().__init__(n, k)
        self.matrix = gf.systematic_rs_matrix(n, k)
        self._decode_matrix = functools.lru_cache(maxsize=512)(
            self._decode_matrix_impl
        )

    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8)
        assert data.ndim == 2 and data.shape[0] == self.k, data.shape
        if self.n == self.k:
            return data.copy()
        syms = _to_symbols(data)
        parity = gf.gf_matmul(self.matrix[self.k :], syms)
        return np.concatenate([data, _to_bytes(parity)], axis=0)

    def _decode_matrix_impl(self, indices: tuple) -> np.ndarray:
        return gf.gf_mat_inv(self.matrix[list(indices)])

    def _decode_impl(self, indices: tuple, shards: np.ndarray) -> np.ndarray:
        return _to_bytes(
            gf.gf_matmul(self._decode_matrix(indices), _to_symbols(shards))
        )


class Xla16ErasureCoder(ErasureCoder):
    """MXU path: lifted (16n x 16k) bit-matmul, batched across
    instances (mirrors ops/rs_xla.XlaErasureCoder)."""

    MAX_N = gf.ORDER

    def __init__(self, n: int, k: int, mesh=None):
        super().__init__(n, k)
        self.mesh = mesh  # accepted for factory symmetry (batch axis
        # sharding rides the same put_flat seam when wired)
        self._cpu = Cpu16ErasureCoder(n, k)
        self.matrix = self._cpu.matrix
        self._g_parity = gf.lift_to_bits(self.matrix[self.k :])
        self._g_decode = functools.lru_cache(maxsize=512)(
            self._g_decode_impl
        )

    def _g_decode_impl(self, indices: tuple) -> np.ndarray:
        return gf.lift_to_bits(gf.gf_mat_inv(self.matrix[list(indices)]))

    # -- single-instance ops (tiny: host path keeps dispatch count
    # down, same policy as the 8-bit XLA coder's host floor) ----------
    def encode(self, data: np.ndarray) -> np.ndarray:
        return self._cpu.encode(data)

    def _decode_impl(self, indices: tuple, shards: np.ndarray) -> np.ndarray:
        return self._cpu._decode_impl(indices, shards)

    # -- batched ops: one lifted matmul for all instances -------------
    def encode_batch(self, data: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        from cleisthenes_tpu.ops.rs16_xla_kernels import encode_kernel_batch

        data = np.ascontiguousarray(data, dtype=np.uint8)
        b, k, L = data.shape
        assert k == self.k
        if self.n == self.k:
            return data.copy()
        syms = data.view("<u2").reshape(b, k, L // 2)
        out = encode_kernel_batch(
            jnp.asarray(self._g_parity), jnp.asarray(syms)
        )
        full = np.asarray(out)  # (b, n, L/2) uint16
        return np.ascontiguousarray(full.astype("<u2")).view(
            np.uint8
        ).reshape(b, self.n, L)

    def decode_batch(
        self, indices: np.ndarray, shards: np.ndarray
    ) -> np.ndarray:
        import jax.numpy as jnp

        from cleisthenes_tpu.ops.rs16_xla_kernels import (
            decode_kernel_shared,
        )

        indices = np.asarray(indices)
        shards = np.ascontiguousarray(shards, dtype=np.uint8)
        b, k, L = shards.shape
        patterns = {tuple(int(i) for i in row) for row in indices}
        if len(patterns) == 1:
            pat = next(iter(patterns))
            self._normalize_indices(pat)
            if pat == tuple(range(self.k)):
                return shards.copy()
            g = self._g_decode(pat)
            syms = shards.view("<u2").reshape(b, k, L // 2)
            out = np.asarray(
                decode_kernel_shared(jnp.asarray(g), jnp.asarray(syms))
            )
            return np.ascontiguousarray(out.astype("<u2")).view(
                np.uint8
            ).reshape(b, k, L)
        return super().decode_batch(indices, shards)


__all__ = ["Cpu16ErasureCoder", "Xla16ErasureCoder"]
