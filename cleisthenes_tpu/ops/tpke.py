"""Threshold encryption (TPKE) and the generic threshold-DH core.

Implements the four-call API the reference specifies but never codes
(reference docs/THRESHOLD_ENCRYPTION-EN.md:33-36):

  TPKE.SetUp    -> ThresholdDealer / TpkeKeys (master pubkey + n shares)
  TPKE.Encrypt  -> Tpke.encrypt (hashed-ElGamal KEM under the master key)
  TPKE.DecShare -> Tpke.dec_share (share + Chaum-Pedersen validity proof)
  TPKE.Decrypt  -> Tpke.combine (Lagrange over any f+1 verified shares,
                   docs/HONEYBADGER-EN.md:40-42)

Scheme: discrete-log threshold ElGamal in the prime-order QR subgroup
of Z_p* (p a 256-bit safe prime, ops/modmath.py).  The dealer Shamir-
shares a secret s with threshold t = f+1; decryption shares are
d_i = c1^{s_i} carrying a Chaum-Pedersen NIZK (Fiat-Shamir over
SHA-256) that log_g(h_i) = log_{c1}(d_i) — so invalid shares from
Byzantine nodes are rejected before combination.  Share verification
is 2 dual-exponentiations per share, batched across all N shares in
one TPU dispatch (the "TPKE-share-verify ops/sec" BASELINE metric).

Security notes (documented, deliberate): hashed-ElGamal KEM + integrity
tag in the random-oracle model; a production deployment would swap the
group seam for a pairing curve and Baek-Zheng CCA2 or a larger prime —
the API and the batched-verify data flow are unchanged by that swap,
which is the point of the BatchCrypto seam.  The dealer is trusted
(standard for HBBFT test/bench deployments; DKG is a protocol-layer
extension).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import hmac
import secrets
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from cleisthenes_tpu.ops.modmath import (
    DEFAULT_GROUP,
    G,
    GroupParams,
    P,
    Q,
    get_engine_degraded,
    host_pow,
    host_pow_batch,
)


def _hash_to_int(*parts: bytes) -> int:
    # one pre-joined update (identical bytes to per-part updates):
    # this runs once per issued/verified share — millions of times in
    # a big lockstep epoch — and 2 C calls beat 2*len(parts)
    h = hashlib.sha256(
        b"".join(
            len(p_).to_bytes(4, "big") + p_ for p_ in parts
        )
    )
    return int.from_bytes(h.digest(), "big")


def _cp_challenge_batch(
    contexts: Sequence[bytes],
    bases: Sequence[int],
    his: Sequence[int],
    ds: Sequence[int],
    a1s: Sequence[int],
    a2s: Sequence[int],
    group: "GroupParams",
) -> List[int]:
    """All of a wave's CP challenges e = H(cp transcript) mod q in one
    batched native hash — byte-identical to mapping ``_hash_to_int``
    over the items (tests assert the equivalence), but the transcript
    rows are assembled as numpy columns and digested in a single
    ctypes crossing instead of ~m Python hash calls.

    Rows are grouped by context length (field offsets are constant
    within a group); a lockstep wave has a handful of context shapes,
    so this stays a couple of matrix fills."""
    from cleisthenes_tpu.ops.hashrows import ints_to_be_rows, sha256_rows

    m = len(contexts)
    if m == 0:
        return []
    nb, q = group.nbytes, group.q
    if m < 64:
        # matrix assembly costs more than it saves on the live path's
        # small hub flushes; identical bytes either way
        return [
            _hash_to_int(
                b"cp", contexts[i], _ibytes(bases[i], nb),
                _ibytes(his[i], nb), _ibytes(ds[i], nb),
                _ibytes(a1s[i], nb), _ibytes(a2s[i], nb),
            )
            % q
            for i in range(m)
        ]
    cols = [
        ints_to_be_rows(vals, nb)
        for vals in (bases, his, ds, a1s, a2s)
    ]
    head_pfx = (2).to_bytes(4, "big") + b"cp"
    heads = [
        head_pfx + len(c).to_bytes(4, "big") + c for c in contexts
    ]
    by_hl: Dict[int, List[int]] = {}
    for i, h in enumerate(heads):
        by_hl.setdefault(len(h), []).append(i)
    field_pfx = np.frombuffer(nb.to_bytes(4, "big"), dtype=np.uint8)
    out: List[int] = [0] * m
    for hl, idxs in by_hl.items():
        k = len(idxs)
        rows = np.empty((k, hl + 5 * (4 + nb)), dtype=np.uint8)
        rows[:, :hl] = np.frombuffer(
            b"".join(heads[i] for i in idxs), dtype=np.uint8
        ).reshape(k, hl)
        off = hl
        sel = np.asarray(idxs, dtype=np.intp)
        for col in cols:
            rows[:, off : off + 4] = field_pfx
            rows[:, off + 4 : off + 4 + nb] = col[sel]
            off += 4 + nb
        digs = sha256_rows(rows)
        for row, i in zip(digs, idxs):
            out[i] = int.from_bytes(row.tobytes(), "big") % q
    return out


def _ibytes(x: int, nbytes: int = 32) -> bytes:
    return x.to_bytes(nbytes, "big")


def is_group_element(x: int, group: GroupParams = DEFAULT_GROUP) -> bool:
    """Strict membership test for the prime-order QR subgroup:
    ``1 < x < P`` and ``x^Q == 1 (mod P)``.

    Rejects 0, the identity, P-1 (the order-2 element) and every
    non-residue — the inputs a Byzantine proposer could use to make all
    honest decryption shares unverifiable forever (each honest node's
    d_i = c1^{s_i} then fails its own CP proof, burning every honest
    sender in the SharePool and stalling _maybe_commit), or to leak
    share parities via the order-2 component.  One ~256-bit modexp on
    host per check; callers run it once per deserialized ciphertext.
    """
    return 1 < x < group.p and host_pow(x, group.q, group) == 1


def hash_to_group(data: bytes, group: GroupParams = DEFAULT_GROUP) -> int:
    """Map bytes to the QR subgroup with unknown discrete log:
    (H(data) mod p)^2 mod p."""
    x = _hash_to_int(b"h2g", data) % group.p
    if x == 0:
        x = 1
    return pow(x, 2, group.p)


# ---------------------------------------------------------------------------
# Shamir secret sharing over Z_q
# ---------------------------------------------------------------------------


def _shamir_shares(
    secret: int, n: int, threshold: int, rng_bytes, q: int = Q
) -> List[int]:
    """Evaluate a random degree-(threshold-1) polynomial with
    f(0)=secret at x = 1..n."""
    nb = max(32, (q.bit_length() + 7) // 8 + 8)  # excess bits: no bias
    coeffs = [secret] + [
        int.from_bytes(rng_bytes(nb), "big") % q for _ in range(threshold - 1)
    ]
    shares = []
    for x in range(1, n + 1):
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % q
        shares.append(acc)
    return shares


@functools.lru_cache(maxsize=4096)
def _lagrange_cached(xs: tuple, q: int) -> tuple:
    out = []
    for i, xi in enumerate(xs):
        num, den = 1, 1
        for j, xj in enumerate(xs):
            if i == j:
                continue
            num = num * xj % q
            den = den * ((xj - xi) % q) % q
        out.append(num * pow(den, -1, q) % q)
    return tuple(out)


def lagrange_coeff_at_zero(xs: Sequence[int], q: int = Q) -> List[int]:
    """lambda_i = prod_{j!=i} x_j / (x_j - x_i) mod q, for interpolation
    at 0 (Shamir recovery, docs/THRESHOLD_ENCRYPTION-EN.md:36).

    Cached by index set: an epoch combines N proposals from largely
    the SAME threshold subset of share indices, and the O(t^2) python
    coefficient loop was measurable at N=64 (t=22)."""
    return list(_lagrange_cached(tuple(xs), q))


# ---------------------------------------------------------------------------
# Generic threshold-DH: keygen, share issuance w/ CP proof, batched verify,
# Lagrange combine.  TPKE and the common coin both instantiate this.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ThresholdPublicKey:
    n: int
    threshold: int
    master: int  # h = g^s
    verification_keys: tuple  # h_i = g^{s_i}, 1-indexed by share x = i+1
    # the group every share op under this key runs in (the modulus
    # seam: a key set carries its own parameters end to end)
    group: GroupParams = DEFAULT_GROUP


@dataclasses.dataclass(frozen=True)
class ThresholdSecretShare:
    index: int  # Shamir x-coordinate (1-based)
    value: int  # s_i


class DhShare(NamedTuple):
    """d = base^{s_i} plus a Chaum-Pedersen proof (e, z) that
    log_g(h_i) == log_base(d).

    A NamedTuple, not a dataclass: a live N=64 epoch creates ~1M of
    these and frozen-dataclass ``__init__`` was a visible profile
    line."""

    index: int
    d: int
    e: int
    z: int


def deal(
    n: int,
    threshold: int,
    seed: Optional[int] = None,
    group: GroupParams = DEFAULT_GROUP,
) -> tuple:
    """Trusted-dealer setup (TPKE.SetUp): master pubkey + n secret
    shares.  Deterministic iff ``seed`` given (tests/benchmarks)."""
    if seed is not None:
        ctr = [0]

        def rng_bytes(k: int) -> bytes:
            out = b""
            while len(out) < k:  # k may exceed one digest (large groups)
                ctr[0] += 1
                out += hashlib.sha256(
                    b"dealer|%d|%d" % (seed, ctr[0])
                ).digest()
            return out[:k]

    else:
        rng_bytes = secrets.token_bytes  # staticcheck: allow[DET001] unseeded dealer keygen
    # 8 excess bytes: the reduction mod q is statistically unbiased
    # (bias < 2^-64), matching _shamir_shares' rule
    s = int.from_bytes(rng_bytes(group.nbytes + 8), "big") % group.q
    shares = _shamir_shares(s, n, threshold, rng_bytes, group.q)
    vks = host_pow_batch([group.g] * (n + 1), [s] + shares, group)
    pub = ThresholdPublicKey(
        n=n,
        threshold=threshold,
        master=vks[0],
        verification_keys=tuple(vks[1:]),
        group=group,
    )
    return pub, [
        ThresholdSecretShare(index=i + 1, value=si)
        for i, si in enumerate(shares)
    ]


def issue_share(
    share: ThresholdSecretShare,
    base: int,
    context: bytes,
    group: GroupParams = DEFAULT_GROUP,
) -> DhShare:
    """d = base^{s_i} with CP proof bound to ``context``."""
    # 8 excess bytes -> unbiased nonce: a biased Schnorr/CP nonce
    # leaks the secret share to a lattice (hidden-number) attack over
    # many observed shares, since z = w + e*s_i is linear in w
    nonce = secrets.token_bytes(  # staticcheck: allow[DET001] CP-proof nonce
        group.nbytes + 8
    )
    w = int.from_bytes(nonce, "big") % group.q
    a1, a2, hi, d = host_pow_batch(
        [group.g, base, group.g, base],
        [w, w, share.value, share.value],
        group,
    )
    nb = group.nbytes
    e = (
        _hash_to_int(
            b"cp", context, _ibytes(base, nb), _ibytes(hi, nb),
            _ibytes(d, nb), _ibytes(a1, nb), _ibytes(a2, nb),
        )
        % group.q
    )
    z = (w + e * share.value) % group.q
    return DhShare(index=share.index, d=d, e=e, z=z)


def issue_shares_batch(
    items: Sequence[tuple],
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
) -> List[DhShare]:
    """Issue MANY shares in one batched exponentiation dispatch.

    ``items``: sequence of ``(share, base, context, vk)`` — ``vk`` is
    the issuer's public verification key g^{s_i} (``None`` recomputes
    it, costing one extra exponentiation per item).  Semantics match
    ``issue_share`` exactly; this is the lockstep executor's path,
    where a synchronous wave issues N^2 coin/decryption shares at once
    (protocol.spmd) instead of one 4-exponentiation batch per share.
    """
    if not items:
        return []
    eng = get_engine_degraded(backend, mesh, group)
    q, g = group.q, group.g
    nbytes = group.nbytes
    # Exponentiations grouped by base — a wave shares a handful of
    # bases (the generator g plus one coin base / ciphertext c1 per
    # instance), which is exactly the fixed-base comb kernel's shape
    # (ModEngine.pow_batch_grouped).
    ws = []
    g_exps: List[int] = []
    by_base: Dict[int, List[int]] = {}
    # ONE urandom draw for the whole wave (a lockstep wave issues
    # ~N^2 shares; per-item token_bytes was one syscall each), sliced
    # per item — same unbiased nonce rule (and reason) as issue_share
    stride = nbytes + 8
    nonce_pool = secrets.token_bytes(  # staticcheck: allow[DET001] CP-proof nonces
        stride * len(items)
    )
    off = 0
    for share, base, _context, vk in items:
        w = int.from_bytes(nonce_pool[off : off + stride], "big") % q
        off += stride
        ws.append(w)
        g_exps.append(w)  # a1 = g^w
        if vk is None:
            g_exps.append(share.value)  # h_i = g^{s_i}
        be = by_base.setdefault(base, [])
        be.append(w)  # a2 = base^w
        be.append(share.value)  # d = base^{s_i}
    base_order = list(by_base)
    groups = [(g, g_exps)] + [(b, by_base[b]) for b in base_order]
    pows = eng.pow_batch_grouped(groups)
    g_res = pows[0]
    base_res = {b: res for b, res in zip(base_order, pows[1:])}
    base_off = {b: 0 for b in base_order}
    g_off = 0
    a1s: List[int] = []
    his: List[int] = []
    a2s: List[int] = []
    ds: List[int] = []
    for share, base, _context, vk in items:
        a1s.append(g_res[g_off])
        g_off += 1
        if vk is None:
            his.append(g_res[g_off])
            g_off += 1
        else:
            his.append(vk)
        bo = base_off[base]
        a2s.append(base_res[base][bo])
        ds.append(base_res[base][bo + 1])
        base_off[base] = bo + 2
    es = _cp_challenge_batch(
        [it[2] for it in items],
        [it[1] for it in items],
        his,
        ds,
        a1s,
        a2s,
        group,
    )
    return [
        DhShare(
            index=share.index,
            d=d,
            e=e,
            z=(w + e * share.value) % q,
        )
        for (share, _b, _c, _vk), w, d, e in zip(items, ws, ds, es)
    ]


def combine_shares_batch(
    share_sets: Sequence[Sequence[DhShare]],
    threshold: int,
    group: GroupParams = DEFAULT_GROUP,
    backend: str = "cpu",
    mesh=None,
) -> List[int]:
    """Lagrange-combine many independent share sets in ONE
    exponentiation dispatch (each set >= threshold verified shares;
    result order matches input order).  Equivalent to mapping
    ``combine_shares``, and shares its memo."""
    if not share_sets:
        return []
    eng = get_engine_degraded(backend, mesh, group)
    results: List[Optional[int]] = [None] * len(share_sets)
    bases_flat: List[int] = []
    exps_flat: List[int] = []
    spans: List[tuple] = []  # (set_idx, memo_key, n_terms)
    for si, shares in enumerate(share_sets):
        if len(shares) < threshold:
            raise ValueError(
                f"need >= {threshold} shares to combine, got {len(shares)}"
            )
        use = sorted(shares, key=lambda s: s.index)[:threshold]
        xs = [s.index for s in use]
        if len(set(xs)) != len(xs):
            raise ValueError("duplicate share indices")
        key = (group, threshold, tuple((s.index, s.d) for s in use))
        hit = _COMBINE_MEMO.get(key)
        if hit is not None:
            results[si] = hit
            continue
        lams = lagrange_coeff_at_zero(xs, group.q)
        bases_flat.extend(sh.d % group.p for sh in use)
        exps_flat.extend(lams)
        spans.append((si, key, threshold))
    if bases_flat:
        pows = eng.pow_batch(bases_flat, exps_flat)
        off = 0
        for si, key, n_terms in spans:
            acc = 1
            for term in pows[off : off + n_terms]:
                acc = acc * term % group.p
            off += n_terms
            if len(_COMBINE_MEMO) >= _COMBINE_MEMO_CAP:
                _COMBINE_MEMO.clear()
            _COMBINE_MEMO[key] = acc
            results[si] = acc
    return results  # type: ignore[return-value]


def verify_share_groups(
    groups: Sequence[tuple],
    backend: str = "cpu",
    mesh=None,
) -> List[List[bool]]:
    """Batched CP verification across heterogeneous groups.

    ``groups`` is a sequence of ``(pub, base, shares, context)`` — e.g.
    one group per (proposer ciphertext) or per (BBA instance, round)
    coin — and ALL of their CP proofs run as ONE dual-exponentiation
    dispatch: recompute A1 = g^z * h_i^{-e}, A2 = base^z * d^{-e},
    accept iff e == H(transcript).  This is the cross-instance batching
    the protocol hub uses: an epoch's N TPKE ciphertexts and its
    concurrent BBA coins verify together instead of one dispatch per
    instance (the reference's cost model is 4N^2 shares/epoch,
    docs/HONEYBADGER-EN.md:93-94).
    """
    if not groups:
        return []
    # one engine (and one batched dispatch) per distinct GroupParams;
    # in practice a node's TPKE and coin keys share one group, so this
    # stays a single dispatch
    by_gp: Dict[GroupParams, List[int]] = {}
    for gi, (pub, _base, _shares, _context) in enumerate(groups):
        by_gp.setdefault(pub.group, []).append(gi)
    results: Dict[int, List[bool]] = {}
    for gp, idx_list in by_gp.items():
        eng = get_engine_degraded(backend, mesh, gp)
        # NOTE: a comb-decomposed variant (g^z, h^{-e}, base^z grouped
        # fixed-base; d^{-e} generic; host recombination) was measured
        # SLOWER than this fused path at 4k checks (0.23 s vs 0.12 s
        # warm on the v5e relay): Shamir's trick already shares the
        # square chain between both factors of each dual, so the
        # decomposition saves fewer multiplies than it spends on extra
        # dispatches and host marshalling.
        a = _verify_pows_dual(gp, eng, groups, idx_list)
        results.update(_cp_verdicts(gp, groups, idx_list, a))
    return [results[gi] for gi in range(len(groups))]


def _verify_dual_items(gp, groups, idx_list):
    """The (u1, e1, u2, e2) dual-exponentiation lists recomputing
    (A1, A2) for every share of ``idx_list``'s groups — shared by the
    plain and the fused verifiers so the two can never drift."""
    u1, e1, u2, e2 = [], [], [], []
    for gi in idx_list:
        pub, base, shares, _context = groups[gi]
        for sh in shares:
            if not (1 <= sh.index <= pub.n):
                # out-of-roster index: verified vacuously false by
                # pinning to vk=1 (never matches a real transcript)
                hi = 1
            else:
                hi = pub.verification_keys[sh.index - 1]
            neg_e = (-sh.e) % gp.q
            # A1 = g^z * hi^{-e}
            u1.append(gp.g); e1.append(sh.z % gp.q)
            u2.append(hi); e2.append(neg_e)
            # A2 = base^z * d^{-e}
            u1.append(base); e1.append(sh.z % gp.q)
            u2.append(sh.d % gp.p); e2.append(neg_e)
    return u1, e1, u2, e2


def _cp_verdicts(gp, groups, idx_list, a) -> Dict[int, List[bool]]:
    """Verdicts from the recomputed (A1, A2) stream ``a`` (two entries
    per share, idx_list order): assemble every transcript, run ONE
    batched challenge hash, compare — shared by the plain and fused
    verifiers."""
    off = 0
    ctxs: List[bytes] = []
    basel: List[int] = []
    hil: List[int] = []
    dl: List[int] = []
    a1l: List[int] = []
    a2l: List[int] = []
    struct_ok: List[bool] = []
    want_e: List[int] = []
    for gi in idx_list:
        pub, base, shares, context = groups[gi]
        for sh in shares:
            a1, a2 = a[off], a[off + 1]
            off += 2
            ok = (1 <= sh.index <= pub.n) and (0 < sh.d < gp.p)
            hi = pub.verification_keys[sh.index - 1] if ok else 1
            ctxs.append(context)
            basel.append(base)
            hil.append(hi)
            dl.append(sh.d % gp.p)
            a1l.append(a1)
            a2l.append(a2)
            struct_ok.append(ok)
            want_e.append(sh.e % gp.q)
    es = _cp_challenge_batch(ctxs, basel, hil, dl, a1l, a2l, gp)
    results: Dict[int, List[bool]] = {}
    k = 0
    for gi in idx_list:
        _pub, _base, shares, _context = groups[gi]
        res = []
        for _sh in shares:
            res.append(struct_ok[k] and es[k] == want_e[k])
            k += 1
        results[gi] = res
    return results


def _verify_pows_dual(gp, eng, groups, idx_list) -> List[int]:
    """(A1, A2) per share via the fused dual-exponentiation kernel —
    the host path and the small-batch device path."""
    u1, e1, u2, e2 = _verify_dual_items(gp, groups, idx_list)
    return eng.dual_pow_batch(u1, e1, u2, e2)


def verify_and_combine_share_groups(
    groups: Sequence[tuple],
    threshold: int,
    backend: str = "cpu",
    mesh=None,
    combine_only_sets: Sequence[Sequence[DhShare]] = (),
    combine_only_group: Optional[GroupParams] = None,
) -> Tuple[List[List[bool]], List[Optional[int]], List[int]]:
    """Verify every group's CP proofs AND Lagrange-combine each group's
    first ``threshold`` shares in ONE fused dual-exponentiation
    dispatch (half the device round-trips of verify + combine run
    separately — the lockstep BBA's per-round critical path).

    ``groups`` is ``(pub, base, shares, context)`` as in
    ``verify_share_groups``; returns ``(verdicts, values)`` where
    ``values[i]`` is the combination of group i's shares (``None``
    when the group has fewer than ``threshold`` shares).  Combination
    does not wait for the verdicts — callers must discard the value
    of any group whose verdicts fail (the lockstep executor asserts
    them; the live path uses the unfused ops).  Results seed the
    combine memo, so a later ``combine_shares`` on the same subset is
    a pure host hit.

    ``combine_only_sets`` are additional share sets (same threshold,
    group ``combine_only_group`` — defaults to the first group's) to
    Lagrange-combine WITHOUT verification in the same dispatch: the
    lockstep executor rides its whole optimistic-decrypt wave on BBA
    round 0's device round-trip this way.  Their values are the third
    returned list."""
    if not groups and not combine_only_sets:
        return [], [], []
    by_gp: Dict[GroupParams, List[int]] = {}
    for gi, (pub, _base, _shares, _context) in enumerate(groups):
        by_gp.setdefault(pub.group, []).append(gi)
    co_gp: Optional[GroupParams] = None
    if combine_only_sets:
        if combine_only_group is not None:
            co_gp = combine_only_group
        elif groups:
            co_gp = groups[0][0].group
        else:
            # guessing a group here would produce a well-formed but
            # cryptographically WRONG combination (and memoize it)
            raise ValueError(
                "combine_only_sets without groups requires an "
                "explicit combine_only_group"
            )
        by_gp.setdefault(co_gp, [])
    verdicts: Dict[int, List[bool]] = {}
    values: Dict[int, Optional[int]] = {}
    co_values: List[int] = [0] * len(combine_only_sets)
    for gp, idx_list in by_gp.items():
        eng = get_engine_degraded(backend, mesh, gp)
        # verification duals first (2 per share), then combine terms
        # (threshold per set) ride the same dispatch as u2^0 = 1
        # dummy-factor duals
        u1, e1, u2, e2 = _verify_dual_items(gp, groups, idx_list)
        n_dual = len(u1)
        comb_spans: List[tuple] = []  # (store(value), memo_key)

        def queue_combine(shares, store) -> None:
            """Memo-hit now or queue threshold Lagrange terms; the
            post-dispatch loop below routes the product to ``store``.
            One body for both the verified groups and the
            combine-only sets — they cannot drift."""
            use = sorted(shares, key=lambda s: s.index)[:threshold]
            xs = [s.index for s in use]
            if len(set(xs)) != len(xs):
                raise ValueError("duplicate share indices")
            key = (gp, threshold, tuple((s.index, s.d) for s in use))
            hit = _COMBINE_MEMO.get(key)
            if hit is not None:
                store(hit)
                return
            lams = lagrange_coeff_at_zero(xs, gp.q)
            for sh, lam in zip(use, lams):
                u1.append(sh.d % gp.p); e1.append(lam)
                u2.append(1); e2.append(0)
            comb_spans.append((store, key))

        for gi in idx_list:
            pub, _base, shares, _context = groups[gi]
            if len(shares) < threshold:
                values[gi] = None
                continue
            queue_combine(
                shares, lambda v, gi=gi: values.__setitem__(gi, v)
            )
        if gp == co_gp:  # equality, not identity: by_gp keys by value
            for ci, shares in enumerate(combine_only_sets):
                if len(shares) < threshold:
                    raise ValueError(
                        f"need >= {threshold} shares, got {len(shares)}"
                    )
                queue_combine(
                    shares, lambda v, ci=ci: co_values.__setitem__(ci, v)
                )
        a = eng.dual_pow_batch(u1, e1, u2, e2)
        verdicts.update(_cp_verdicts(gp, groups, idx_list, a))
        off = n_dual
        for store, key in comb_spans:
            acc = 1
            for term in a[off : off + threshold]:
                acc = acc * term % gp.p
            off += threshold
            if len(_COMBINE_MEMO) >= _COMBINE_MEMO_CAP:
                _COMBINE_MEMO.clear()
            _COMBINE_MEMO[key] = acc
            store(acc)
    return (
        [verdicts[gi] for gi in range(len(groups))],
        [values[gi] for gi in range(len(groups))],
        co_values,
    )


def verify_shares(
    pub: ThresholdPublicKey,
    base: int,
    shares: Sequence[DhShare],
    context: bytes,
    backend: str = "cpu",
    mesh=None,
) -> List[bool]:
    """Single-group convenience over ``verify_share_groups``."""
    if not shares:
        return []
    return verify_share_groups(
        [(pub, base, shares, context)], backend, mesh
    )[0]


class SharePool:
    """Sender-keyed pool of DhShares with deferred batched verification.

    One slot per roster sender (an honest node submits exactly one
    share per context), so a Byzantine peer can only ever occupy — and
    then burn — its own slot: a sender whose share fails verification
    is remembered in ``_burned`` and can never resubmit, bounding both
    memory and re-verification work.  Valid shares are deduped by
    Shamir index before combination (a Byzantine sender may replay
    another node's valid share, which must not trip the distinct-
    index requirement of Lagrange interpolation).

    Shares sit in a *pending* set until verification verdicts arrive —
    either via ``try_verified`` (self-contained, one verify call per
    pool) or via ``collect_pending``/``apply_verdicts`` driven by the
    protocol hub, which verifies MANY pools' pending shares in one
    cross-instance dispatch (protocol.hub.CryptoHub).

    Shared by the BBA common coin and the TPKE decryption path — the
    two consumers of threshold shares in HBBFT.
    """

    __slots__ = ("threshold", "_pending", "_verified", "_burned",
                 "_seen", "_lazy", "_n", "_idx_cover")

    def __init__(self, threshold: int):
        self.threshold = threshold
        self._pending: Dict[str, DhShare] = {}
        self._verified: Dict[str, DhShare] = {}
        self._burned: set = set()
        # one membership set over pending+verified+burned+lazy: the
        # add paths make a single probe instead of three
        self._seen: set = set()
        # lazily-parked (sender, index, d, e, z) rows: the live path's
        # wave handlers park ~N shares per pool but only ~threshold
        # ever get consumed — DhShare objects materialize on first
        # structured access, so arrival cost is probe+append
        self._lazy: List[tuple] = []
        self._n = 0  # pending+verified+lazy (burns decrement)
        # distinct Shamir indices held (pending+verified+lazy) — an
        # upper bound on achievable interpolation coverage, letting
        # lazy row-store pulls stop the moment the threshold is
        # coverable instead of materializing a whole wave (recomputed
        # exactly when a burn invalidates it)
        self._idx_cover: set = set()

    def covered(self) -> int:
        return len(self._idx_cover)

    def add(self, sender: str, share: DhShare) -> bool:
        """First share per non-burned sender wins."""
        if sender in self._seen:
            return False
        self._seen.add(sender)
        self._pending[sender] = share
        self._idx_cover.add(share.index)
        self._n += 1
        return True

    def add_lazy(
        self, sender: str, index: int, d: int, e: int, z: int
    ) -> bool:
        """``add`` without constructing the DhShare: the batched wave
        handlers' per-share fast path."""
        if sender in self._seen:
            return False
        self._seen.add(sender)
        self._lazy.append((sender, index, d, e, z))
        self._idx_cover.add(index)
        self._n += 1
        return True

    def _materialize(self) -> None:
        if self._lazy:
            pending = self._pending
            for sender, index, d, e, z in self._lazy:
                pending[sender] = DhShare(index, d, e, z)
            self._lazy.clear()

    def __len__(self) -> int:
        """Potential size: pending + verified (the threshold trigger)."""
        return self._n

    def collect_pending(
        self, limit: Optional[int] = None
    ) -> Tuple[List[str], List[DhShare]]:
        """Unverified shares for an external batched verify.

        ``limit=None`` returns everything.  The hub passes
        ``need_more()`` instead: only enough pending shares to reach
        the threshold (counting distinct verified indices already
        held), sorted by sender for determinism.  Surplus shares stay
        parked — verifying a full wave's N shares when f+1 suffice is
        pure modexp waste (the round-3 wave-batching regression: ~2.7x
        the CP checks per pool); if a collected share fails, the next
        flush pulls replacements from the parked surplus.
        """
        self._materialize()
        if limit is None:
            senders = list(self._pending)
        else:
            # skip shares whose Shamir index is already covered (a
            # replayed honest share verifies fine but adds no distinct
            # index) — both against the verified set and within the
            # selected slice; skipped shares stay parked as fallback
            have = {s.index for s in self._verified.values()}
            senders = []
            for sender in sorted(self._pending):
                if len(senders) >= max(limit, 0):
                    break
                idx = self._pending[sender].index
                if idx in have:
                    continue
                have.add(idx)
                senders.append(sender)
        return senders, [self._pending[s] for s in senders]

    def need_more(self) -> int:
        """How many additional verified index-distinct shares the
        threshold still needs (0 = ready or no point verifying)."""
        have = len({s.index for s in self._verified.values()})
        return max(self.threshold - have, 0)

    def apply_verdicts(self, senders: Sequence[str], ok: Sequence[bool]) -> None:
        """Record external verification verdicts: valid shares move to
        the verified set, senders of invalid ones burn."""
        burned_any = False
        for sender, good in zip(senders, ok):
            share = self._pending.pop(sender, None)
            if share is None:
                continue
            if good:
                self._verified[sender] = share
            else:
                self._burned.add(sender)
                self._n -= 1
                burned_any = True
        if burned_any:
            # the burned share may have been an index's only holder:
            # recompute the coverage bound exactly (rare path)
            self._idx_cover = {
                s.index for s in self._pending.values()
            } | {s.index for s in self._verified.values()} | {
                row[1] for row in self._lazy
            }

    def ready(self) -> Optional[List[DhShare]]:
        """>= threshold index-distinct verified shares, or None."""
        by_index: Dict[int, DhShare] = {}
        for share in self._verified.values():
            by_index.setdefault(share.index, share)
        if len(by_index) < self.threshold:
            return None
        return list(by_index.values())

    def optimistic_subset(self) -> Optional[List[DhShare]]:
        """Threshold index-distinct shares counting UNVERIFIED ones
        (verified preferred, then pending by sender order), or None.

        For consumers whose combined output is self-authenticating
        (TPKE: the ciphertext tag checks the combined KEM value), an
        optimistic combine on this subset replaces per-share CP
        verification in the honest case entirely; a tag failure means
        some selected share was invalid, and the caller falls back to
        the verified path, which burns the culprit.  NOT safe for the
        common coin — its combined value has no independent check."""
        self._materialize()
        by_index: Dict[int, DhShare] = {}
        for share in self._verified.values():
            by_index.setdefault(share.index, share)
        for sender in sorted(self._pending):
            share = self._pending[sender]
            by_index.setdefault(share.index, share)
        if len(by_index) < self.threshold:
            return None
        return list(by_index.values())

    def try_verified(self, verify_fn) -> Optional[List[DhShare]]:
        """Self-contained threshold check: if >= threshold shares are
        pooled, batch-verify the pending ones (``verify_fn(shares) ->
        List[bool]``, ONE dispatch under 'tpu'), burn invalid senders,
        and return >= threshold index-distinct valid shares — or None
        if not there yet."""
        if len(self) < self.threshold:
            return None
        senders, shares = self.collect_pending()
        if shares:
            self.apply_verdicts(senders, verify_fn(shares))
        return self.ready()


# The combined value is a pure function of (group, threshold, the
# chosen subset's (index, d) pairs) — z/e play no part in combining.
# Every node of a cluster combines the same subset for the same coin
# or ciphertext, so a bounded memo turns N identical ~threshold-sized
# exponentiation batches into one (cleared wholesale at the cap; keys
# carry the share values, so distinct inputs can never collide).
# Entries hold threshold-many group elements (KBs at large N), so the
# cap is deliberately small; a working set is ~2N live combines.
_COMBINE_MEMO: Dict[tuple, int] = {}
_COMBINE_MEMO_CAP = 1 << 12


def combine_shares(
    shares: Sequence[DhShare],
    threshold: int,
    group: GroupParams = DEFAULT_GROUP,
) -> int:
    """Lagrange-combine >= threshold verified shares into base^s."""
    if len(shares) < threshold:
        raise ValueError(
            f"need >= {threshold} shares to combine, got {len(shares)}"
        )
    use = sorted(shares, key=lambda s: s.index)[:threshold]
    xs = [s.index for s in use]
    if len(set(xs)) != len(xs):
        raise ValueError("duplicate share indices")
    key = (group, threshold, tuple((s.index, s.d) for s in use))
    hit = _COMBINE_MEMO.get(key)
    if hit is not None:
        return hit
    lams = lagrange_coeff_at_zero(xs, group.q)
    acc = 1
    for term in host_pow_batch([sh.d % group.p for sh in use], lams, group):
        acc = acc * term % group.p
    if len(_COMBINE_MEMO) >= _COMBINE_MEMO_CAP:
        _COMBINE_MEMO.clear()
    _COMBINE_MEMO[key] = acc
    return acc


# ---------------------------------------------------------------------------
# TPKE proper: hashed-ElGamal KEM over the threshold-DH core
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Ciphertext:
    c1: int  # g^r
    c2: bytes  # msg XOR keystream
    tag: bytes  # integrity tag binding (key, c1, c2)


def _keystream(key: bytes, length: int) -> bytes:
    n_blocks = (length + 31) // 32
    if n_blocks >= 16:
        # batch-size payloads (tens of KB per proposer): hash every
        # counter block in one native crossing — byte-identical to
        # the scalar loop below
        from cleisthenes_tpu.ops.hashrows import sha256_rows

        k = len(key)
        rows = np.empty((n_blocks, k + 6), dtype=np.uint8)
        rows[:, :k] = np.frombuffer(key, dtype=np.uint8)
        rows[:, k : k + 4] = (
            np.arange(n_blocks, dtype=">u4")
            .view(np.uint8)
            .reshape(n_blocks, 4)
        )
        rows[:, k + 4] = ord("k")
        rows[:, k + 5] = ord("s")
        return sha256_rows(rows).tobytes()[:length]
    out = []
    ctr = 0
    while 32 * len(out) < length:
        out.append(
            hashlib.sha256(key + ctr.to_bytes(4, "big") + b"ks").digest()
        )
        ctr += 1
    return b"".join(out)[:length]


def _xor_bytes(a: bytes, b: bytes) -> bytes:
    """a ^ b over equal-length byte strings, vectorized: the stream
    cipher runs over whole proposed batches (tens of KB per proposer),
    where a per-byte python loop costs more than the group math."""
    import numpy as np

    return (
        np.frombuffer(a, dtype=np.uint8) ^ np.frombuffer(b, dtype=np.uint8)
    ).tobytes()


class Tpke:
    """Threshold decryption service for one key set."""

    def __init__(
        self, pub: ThresholdPublicKey, backend: str = "cpu", mesh=None
    ):
        self.pub = pub
        self.backend = backend
        self.mesh = mesh
        self.group = pub.group  # the key set carries its group

    # TPKE.Encrypt (docs/THRESHOLD_ENCRYPTION-EN.md:34)
    def encrypt(self, msg: bytes, rng=secrets) -> Ciphertext:
        gp = self.group
        # 8 excess bytes: unbiased KEM exponent (same rule as
        # _shamir_shares / issue_share)
        r = (
            int.from_bytes(rng.token_bytes(gp.nbytes + 8), "big") % gp.q
        )
        c1, kem = host_pow_batch(
            [gp.g, self.pub.master], [r, r], gp
        )  # g^r, h^r
        key = hashlib.sha256(b"kem" + _ibytes(kem, gp.nbytes)).digest()
        c2 = _xor_bytes(msg, _keystream(key, len(msg)))
        tag = hmac.new(
            key, _ibytes(c1, gp.nbytes) + c2, hashlib.sha256
        ).digest()
        return Ciphertext(c1=c1, c2=c2, tag=tag)

    def context(self, ct: Ciphertext) -> bytes:
        """The CP-proof context binding shares to this ciphertext
        (public: the protocol hub groups cross-instance verifies by
        (pub, base, context))."""
        return (
            b"tpke|"
            + _ibytes(ct.c1, self.group.nbytes)
            + hashlib.sha256(ct.c2).digest()
        )

    _context = context  # internal alias

    # TPKE.DecShare (docs/THRESHOLD_ENCRYPTION-EN.md:35)
    def dec_share(
        self, share: ThresholdSecretShare, ct: Ciphertext
    ) -> DhShare:
        return issue_share(share, ct.c1, self._context(ct), self.group)

    def dec_share_items(
        self, share: ThresholdSecretShare, cts: Sequence[Ciphertext]
    ) -> List[tuple]:
        """The ``(share, base, context, vk)`` rows
        ``issue_shares_batch`` takes for this key set — the ONE place
        the CP-proof context/vk binding is built, shared by
        ``dec_share_batch`` and the CryptoHub's eager dec-share
        column (K-deep pipelining) so the two issue paths can never
        bind different contexts."""
        vk = self.pub.verification_keys[share.index - 1]
        return [(share, ct.c1, self._context(ct), vk) for ct in cts]

    def dec_share_batch(
        self, share: ThresholdSecretShare, cts: Sequence[Ciphertext]
    ) -> List[DhShare]:
        """All of an epoch's decryption shares in ONE batched
        exponentiation dispatch and one CP-nonce entropy draw —
        semantically ``[dec_share(share, ct) for ct in cts]`` (the
        wave-columnar protocol path's issue seam; scalar dec_share
        was N 4-exp calls + N urandom reads per node per epoch)."""
        if not cts:
            return []
        return issue_shares_batch(
            self.dec_share_items(share, cts),
            group=self.group,
            backend=self.backend,
            mesh=self.mesh,
        )

    def verify_dec_shares(
        self, ct: Ciphertext, shares: Sequence[DhShare]
    ) -> List[bool]:
        return verify_shares(
            self.pub, ct.c1, shares, self._context(ct), self.backend,
            self.mesh,
        )

    # TPKE.Decrypt (docs/THRESHOLD_ENCRYPTION-EN.md:36)
    def combine(
        self, ct: Ciphertext, shares: Sequence[DhShare]
    ) -> bytes:
        """Recover the plaintext from >= f+1 *verified* shares.

        Raises ValueError if the integrity tag does not check out —
        deterministically for every correct node, since the combined
        KEM value is independent of which valid share subset was used.
        """
        kem = combine_shares(shares, self.pub.threshold, self.group)
        key = hashlib.sha256(b"kem" + _ibytes(kem, self.group.nbytes)).digest()
        tag = hmac.new(
            key, _ibytes(ct.c1, self.group.nbytes) + ct.c2, hashlib.sha256
        ).digest()
        if not hmac.compare_digest(tag, ct.tag):
            raise ValueError("TPKE integrity check failed")
        return _xor_bytes(ct.c2, _keystream(key, len(ct.c2)))


__all__ = [
    "is_group_element",
    "ThresholdPublicKey",
    "ThresholdSecretShare",
    "DhShare",
    "SharePool",
    "Ciphertext",
    "deal",
    "issue_share",
    "issue_shares_batch",
    "verify_shares",
    "verify_share_groups",
    "verify_and_combine_share_groups",
    "combine_shares",
    "combine_shares_batch",
    "lagrange_coeff_at_zero",
    "hash_to_group",
    "Tpke",
]
