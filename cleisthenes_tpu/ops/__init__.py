"""The crypto plane: batched, fixed-shape TPU kernels + CPU references.

This package is the TPU-native replacement for the reference's native
hot loops (SURVEY.md §2.3): the GF(2^8) Reed-Solomon codec that the
reference takes from klauspost/reedsolomon's SIMD assembly
(reference go.mod:10, rbc/rbc.go:7,21,98), the SHA-256 Merkle forest
(reference docs/RBC-EN.md:31-45), and the modular-arithmetic engine
behind threshold encryption and the common coin
(reference docs/THRESHOLD_ENCRYPTION-EN.md:33-36, docs/BBA-EN.md:163-181).
"""

from cleisthenes_tpu.ops.backend import (
    BatchCrypto,
    ErasureCoder,
    get_backend,
)

__all__ = ["BatchCrypto", "ErasureCoder", "get_backend"]
