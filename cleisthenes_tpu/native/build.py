"""On-demand compilation + ctypes loading of the native kernels."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

_SRC = Path(__file__).with_name("gf256.cpp")
_LIB_CACHE: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False


def _cache_path() -> Path:
    """Library path keyed by source hash (rebuilds on source change)."""
    digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    name = f"_gf256-{digest}.so"
    local = _SRC.parent / name
    if os.access(_SRC.parent, os.W_OK):
        return local
    cache_dir = Path(tempfile.gettempdir()) / "cleisthenes_tpu_native"
    cache_dir.mkdir(parents=True, exist_ok=True)
    return cache_dir / name


def _compile(out: Path) -> None:
    # per-process tmp name: concurrent first-time builders must not
    # interleave writes before the atomic rename
    tmp = out.with_suffix(f".tmp{os.getpid()}.so")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-funroll-loops", str(_SRC), "-o", str(tmp),
    ]
    subprocess.run(
        cmd, check=True, capture_output=True, timeout=120
    )
    tmp.replace(out)  # atomic: concurrent builders race benignly


def load_gf256() -> Optional[ctypes.CDLL]:
    """The compiled library, or None if unavailable (no toolchain)."""
    global _LIB_CACHE, _LOAD_FAILED
    if _LIB_CACHE is not None or _LOAD_FAILED:
        return _LIB_CACHE
    try:
        path = _cache_path()
        if not path.exists():
            _compile(path)
        lib = ctypes.CDLL(str(path))
        lib.gf256_matmul.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.gf256_matmul_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.gf256_selftest.restype = ctypes.c_int
        rc = lib.gf256_selftest()
        if rc != 0:
            raise RuntimeError(f"gf256 selftest failed: {rc}")
        _LIB_CACHE = lib
    except Exception:
        _LOAD_FAILED = True
        _LIB_CACHE = None
    return _LIB_CACHE


def native_available() -> bool:
    return load_gf256() is not None


__all__ = ["load_gf256", "native_available"]
