"""On-demand compilation + ctypes loading of the native kernels.

Each kernel source compiles to a shared library cached by source hash
(rebuilds on change, races benignly via atomic rename); loading is
attempted once per process and failure degrades to the pure-python /
XLA paths, never to an exception.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from pathlib import Path
from typing import Callable, Dict, Optional

_DIR = Path(__file__).parent
_LIBS: Dict[str, Optional[ctypes.CDLL]] = {}


def _cache_path(src: Path) -> Path:
    """Library path keyed by source hash (rebuilds on source change)."""
    digest = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    name = f"_{src.stem}-{digest}.so"
    if os.access(src.parent, os.W_OK):
        return src.parent / name
    cache_dir = Path(tempfile.gettempdir()) / "cleisthenes_tpu_native"
    cache_dir.mkdir(parents=True, exist_ok=True)
    return cache_dir / name


def _compile(src: Path, out: Path) -> None:
    # per-process tmp name: concurrent first-time builders must not
    # interleave writes before the atomic rename
    tmp = out.with_suffix(f".tmp{os.getpid()}.so")
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        "-funroll-loops", "-pthread", str(src), "-o", str(tmp),
    ]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    tmp.replace(out)  # atomic: concurrent builders race benignly


def _load(name: str, configure: Callable[[ctypes.CDLL], None]):
    """Compile-if-needed + load + configure + selftest, once per
    process; returns None forever after the first failure."""
    if name in _LIBS:
        return _LIBS[name]
    try:
        src = _DIR / f"{name}.cpp"
        path = _cache_path(src)
        if not path.exists():
            _compile(src, path)
        lib = ctypes.CDLL(str(path))
        configure(lib)
        _LIBS[name] = lib
    except Exception:
        _LIBS[name] = None
    return _LIBS[name]


def _configure_gf256(lib: ctypes.CDLL) -> None:
    lib.gf256_matmul.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.gf256_matmul_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.gf256_selftest.restype = ctypes.c_int
    rc = lib.gf256_selftest()
    if rc != 0:
        raise RuntimeError(f"gf256 selftest failed: {rc}")


def _configure_modpow(lib: ctypes.CDLL) -> None:
    lib.modpow256_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int,
    ]
    lib.dualpow256_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int,
    ]
    lib.modpow256_selftest.restype = ctypes.c_int
    rc = lib.modpow256_selftest()
    if rc != 0:
        raise RuntimeError(f"modpow256 selftest failed: {rc}")


def _configure_sha256(lib: ctypes.CDLL) -> None:
    lib.sha256_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.sha256_rows_fixed.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.sha256_selftest.restype = ctypes.c_int
    rc = lib.sha256_selftest()
    if rc != 0:
        raise RuntimeError(f"sha256rows selftest failed: {rc}")


def load_sha256() -> Optional[ctypes.CDLL]:
    """The batched SHA-256 library, or None (no toolchain)."""
    return _load("sha256rows", _configure_sha256)


def load_gf256() -> Optional[ctypes.CDLL]:
    """The GF(2^8) RS kernel library, or None (no toolchain)."""
    return _load("gf256", _configure_gf256)


def load_modpow() -> Optional[ctypes.CDLL]:
    """The 256-bit Montgomery modexp library, or None."""
    return _load("modpow256", _configure_modpow)


def native_available() -> bool:
    return load_gf256() is not None


__all__ = ["load_gf256", "load_modpow", "load_sha256", "native_available"]
