// Batched SHA-256 over many short messages (one C call per wave).
//
// The protocol's hot host loops hash hundreds of thousands of small
// fixed-layout transcripts per lockstep epoch (Chaum-Pedersen
// challenges in ops/tpke.py, Merkle leaf/node digests in
// ops/merkle.py's host path).  Per-message hashlib calls spend more
// time in Python call overhead than in compression; this kernel takes
// the whole wave as one padded row-matrix and returns all digests in
// a single crossing.  Implemented from FIPS 180-4 (same spec as
// ops/sha256_xla.py, which is the device-side twin).
//
// Layout: msgs is (m, stride) row-major uint8, row i holds lens[i]
// message bytes (rest ignored); out is (m, 32).

#include <cstdint>
#include <cstring>

#include <dlfcn.h>

#include <initializer_list>

namespace {

// OpenSSL's SHA256 one-shot (hardware SHA-NI where the CPU has it,
// ~2x this file's portable loop).  Resolved at first use via dlopen
// so the build needs no -dev headers; the portable path below is the
// always-available fallback and the selftest cross-checks them.
typedef unsigned char* (*openssl_sha256_fn)(const unsigned char*,
                                            size_t, unsigned char*);

openssl_sha256_fn resolve_openssl() {
    static openssl_sha256_fn fn = nullptr;
    static bool tried = false;
    if (!tried) {
        tried = true;
        for (const char* name :
             {"libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"}) {
            if (void* h = dlopen(name, RTLD_LAZY | RTLD_GLOBAL)) {
                fn = reinterpret_cast<openssl_sha256_fn>(
                    dlsym(h, "SHA256"));
                if (fn) break;
            }
        }
    }
    return fn;
}

inline uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

const uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
    0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
    0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
    0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
    0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
    0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
    0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
    0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
    0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
    0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
    0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

void compress(uint32_t state[8], const uint8_t block[64]) {
    uint32_t w[64];
    for (int t = 0; t < 16; t++) {
        w[t] = (uint32_t(block[4 * t]) << 24) |
               (uint32_t(block[4 * t + 1]) << 16) |
               (uint32_t(block[4 * t + 2]) << 8) |
               uint32_t(block[4 * t + 3]);
    }
    for (int t = 16; t < 64; t++) {
        uint32_t s0 = rotr(w[t - 15], 7) ^ rotr(w[t - 15], 18) ^
                      (w[t - 15] >> 3);
        uint32_t s1 = rotr(w[t - 2], 17) ^ rotr(w[t - 2], 19) ^
                      (w[t - 2] >> 10);
        w[t] = w[t - 16] + s0 + w[t - 7] + s1;
    }
    uint32_t a = state[0], b = state[1], c = state[2], d = state[3];
    uint32_t e = state[4], f = state[5], g = state[6], h = state[7];
    for (int t = 0; t < 64; t++) {
        uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K[t] + w[t];
        uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    state[0] += a; state[1] += b; state[2] += c; state[3] += d;
    state[4] += e; state[5] += f; state[6] += g; state[7] += h;
}

void sha256_one(const uint8_t* msg, int64_t len, uint8_t out[32]) {
    uint32_t st[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                      0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    int64_t off = 0;
    for (; off + 64 <= len; off += 64) compress(st, msg + off);
    uint8_t tail[128];
    int64_t rem = len - off;
    std::memcpy(tail, msg + off, rem);
    tail[rem] = 0x80;
    int64_t pad = (rem + 1 <= 56) ? 64 : 128;
    std::memset(tail + rem + 1, 0, pad - rem - 1 - 8);
    uint64_t bits = uint64_t(len) * 8;
    for (int i = 0; i < 8; i++)
        tail[pad - 1 - i] = uint8_t(bits >> (8 * i));
    compress(st, tail);
    if (pad == 128) compress(st, tail + 64);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = uint8_t(st[i] >> 24);
        out[4 * i + 1] = uint8_t(st[i] >> 16);
        out[4 * i + 2] = uint8_t(st[i] >> 8);
        out[4 * i + 3] = uint8_t(st[i]);
    }
}

}  // namespace

extern "C" {

// msgs: (m, stride) row-major; lens: per-row byte counts (lens[i] <=
// stride); out: (m, 32).
void sha256_rows(const uint8_t* msgs, int64_t m, int64_t stride,
                 const int32_t* lens, uint8_t* out) {
    if (openssl_sha256_fn fn = resolve_openssl()) {
        for (int64_t i = 0; i < m; i++)
            fn(msgs + i * stride, size_t(lens[i]), out + i * 32);
        return;
    }
    for (int64_t i = 0; i < m; i++)
        sha256_one(msgs + i * stride, lens[i], out + i * 32);
}

// Equal-length fast path (no lens array needed).
void sha256_rows_fixed(const uint8_t* msgs, int64_t m, int64_t len,
                       int64_t stride, uint8_t* out) {
    if (openssl_sha256_fn fn = resolve_openssl()) {
        for (int64_t i = 0; i < m; i++)
            fn(msgs + i * stride, size_t(len), out + i * 32);
        return;
    }
    for (int64_t i = 0; i < m; i++)
        sha256_one(msgs + i * stride, len, out + i * 32);
}

int sha256_selftest() {
    // FIPS 180-4 vectors: "abc" and the empty string
    const uint8_t abc[3] = {'a', 'b', 'c'};
    const uint8_t want_abc[32] = {
        0xba, 0x78, 0x16, 0xbf, 0x8f, 0x01, 0xcf, 0xea, 0x41, 0x41,
        0x40, 0xde, 0x5d, 0xae, 0x22, 0x23, 0xb0, 0x03, 0x61, 0xa3,
        0x96, 0x17, 0x7a, 0x9c, 0xb4, 0x10, 0xff, 0x61, 0xf2, 0x00,
        0x15, 0xad};
    const uint8_t want_empty[32] = {
        0xe3, 0xb0, 0xc4, 0x42, 0x98, 0xfc, 0x1c, 0x14, 0x9a, 0xfb,
        0xf4, 0xc8, 0x99, 0x6f, 0xb9, 0x24, 0x27, 0xae, 0x41, 0xe4,
        0x64, 0x9b, 0x93, 0x4c, 0xa4, 0x95, 0x99, 0x1b, 0x78, 0x52,
        0xb8, 0x55};
    uint8_t got[32];
    sha256_one(abc, 3, got);
    if (std::memcmp(got, want_abc, 32) != 0) return 1;
    if (openssl_sha256_fn fn = resolve_openssl()) {
        // the dispatched path must agree with the spec path
        uint8_t got2[32];
        fn(abc, 3, got2);
        if (std::memcmp(got2, want_abc, 32) != 0) return 4;
    }
    sha256_one(abc, 0, got);
    if (std::memcmp(got, want_empty, 32) != 0) return 2;
    // a >64-byte message exercises the two-block tail path
    uint8_t longmsg[100];
    for (int i = 0; i < 100; i++) longmsg[i] = uint8_t(i);
    sha256_one(longmsg, 100, got);
    // spot value computed with hashlib:
    // sha256(bytes(range(100))).hexdigest()[:8] == "bce0aff1"
    if (!(got[0] == 0xbc && got[1] == 0xe0 && got[2] == 0xaf &&
          got[3] == 0xf1))
        return 3;
    return 0;
}

}  // extern "C"
