// Native GF(2^8) Reed-Solomon kernel.
//
// The reference's only genuinely native hot loop is the GF(2^8)
// multiply-accumulate inside klauspost/reedsolomon's SSSE3/AVX2
// assembly (reference go.mod:10, consumed at rbc/rbc.go:98).  This is
// the same computation as portable C++: out = mat (*) data over
// GF(2^8) with the 0x11D (AES-erasure) polynomial, table-driven, with
// the inner byte loop written so the compiler auto-vectorizes the
// XOR/table-gather.  Exposed through ctypes (cleisthenes_tpu.native)
// as the 'cpp' ErasureCoder backend; the Python numpy backend stays
// the correctness reference, the XLA backend the TPU path.

#include <cstdint>
#include <cstring>

namespace {

// log/exp tables for generator 2 over poly 0x11D (matches ops/gf256.py)
struct Tables {
    uint8_t mul[256][256];
    Tables() {
        uint16_t exp[512];
        uint16_t log[256];
        uint16_t x = 1;
        for (int i = 0; i < 255; ++i) {
            exp[i] = static_cast<uint16_t>(x);
            log[x] = static_cast<uint16_t>(i);
            x <<= 1;
            if (x & 0x100) x ^= 0x11D;
        }
        for (int i = 255; i < 512; ++i) exp[i] = exp[i - 255];
        for (int a = 0; a < 256; ++a) {
            mul[0][a] = 0;
            mul[a][0] = 0;
        }
        for (int a = 1; a < 256; ++a)
            for (int b = 1; b < 256; ++b)
                mul[a][b] =
                    static_cast<uint8_t>(exp[log[a] + log[b]]);
    }
};

const Tables& tables() {
    static const Tables t;
    return t;
}

}  // namespace

extern "C" {

// out[m, L] = mat[m, k] (*) data[k, L] over GF(2^8).
// Rows are contiguous; caller owns all buffers.
void gf256_matmul(const uint8_t* mat, const uint8_t* data, uint8_t* out,
                  int m, int k, int len) {
    const Tables& t = tables();
    std::memset(out, 0, static_cast<size_t>(m) * len);
    for (int i = 0; i < m; ++i) {
        uint8_t* orow = out + static_cast<size_t>(i) * len;
        for (int j = 0; j < k; ++j) {
            const uint8_t c = mat[i * k + j];
            if (c == 0) continue;
            const uint8_t* trow = t.mul[c];
            const uint8_t* drow = data + static_cast<size_t>(j) * len;
            if (c == 1) {
                for (int l = 0; l < len; ++l) orow[l] ^= drow[l];
            } else {
                for (int l = 0; l < len; ++l) orow[l] ^= trow[drow[l]];
            }
        }
    }
}

// Batched variant: B independent (m, k) x (k, L) products with a
// shared matrix (the N concurrent RBC instances of one epoch).
void gf256_matmul_batch(const uint8_t* mat, const uint8_t* data,
                        uint8_t* out, int batch, int m, int k, int len) {
    const size_t dstride = static_cast<size_t>(k) * len;
    const size_t ostride = static_cast<size_t>(m) * len;
    for (int b = 0; b < batch; ++b)
        gf256_matmul(mat, data + b * dstride, out + b * ostride, m, k, len);
}

int gf256_selftest() {
    // 2 * 3 = 6, 0x80 * 2 = 0x1D (overflow wraps through the poly)
    const Tables& t = tables();
    if (t.mul[2][3] != 6) return 1;
    if (t.mul[0x80][2] != 0x1D) return 2;
    if (t.mul[0xFF][1] != 0xFF) return 3;
    return 0;
}

}  // extern "C"
