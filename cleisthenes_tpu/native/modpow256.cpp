// Batched 256-bit modular exponentiation (Montgomery, 4x64-bit limbs).
//
// The host-CPU twin of ops/modmath.py's lazy-carry Montgomery TPU
// kernels: the threshold-crypto plane (Chaum-Pedersen share
// verification for TPKE decryption and the BBA common coin — the
// reference's "4N^2 signature sharings per node" cost model,
// docs/HONEYBADGER-EN.md:94) is thousands of independent 256-bit
// modexps per epoch.  CPython's pow() costs ~140 us per 256-bit
// exponentiation; this kernel runs the same math in ~10 us, giving the
// 'cpu'/'cpp' backends an honest native baseline (VERDICT round-2
// item 7) and keeping the live CPU protocol path off the python
// bignum wall.
//
// Conventions: every value crosses the ABI as 32-byte little-endian
// (4 u64 limbs); the modulus must be odd (Montgomery requirement) and
// may be any 256-bit odd integer — the group parameters are inputs,
// not compile-time constants, so alternate primes (ops/modmath.py's
// documented group seam) reuse the same kernel.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

typedef uint64_t u64;
typedef unsigned __int128 u128;

namespace {

struct Ctx {
    u64 n[4];    // modulus
    u64 n0inv;   // -n^-1 mod 2^64
    u64 r2[4];   // R^2 mod n, R = 2^256
    u64 one_m[4];  // R mod n (Montgomery 1)
};

inline bool geq(const u64 a[4], const u64 b[4]) {
    for (int i = 3; i >= 0; --i) {
        if (a[i] != b[i]) return a[i] > b[i];
    }
    return true;
}

inline void sub(u64 a[4], const u64 b[4]) {
    u128 borrow = 0;
    for (int i = 0; i < 4; ++i) {
        u128 d = (u128)a[i] - b[i] - borrow;
        a[i] = (u64)d;
        borrow = (d >> 64) & 1;
    }
}

// CIOS Montgomery product: out = a*b*R^-1 mod n.
inline void mont_mul(const Ctx& c, const u64 a[4], const u64 b[4],
                     u64 out[4]) {
    u64 t[6] = {0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 4; ++i) {
        u128 carry = 0;
        for (int j = 0; j < 4; ++j) {
            u128 s = (u128)a[i] * b[j] + t[j] + carry;
            t[j] = (u64)s;
            carry = s >> 64;
        }
        u128 s = (u128)t[4] + carry;
        t[4] = (u64)s;
        t[5] = (u64)(s >> 64);

        u64 m = t[0] * c.n0inv;
        carry = ((u128)m * c.n[0] + t[0]) >> 64;
        for (int j = 1; j < 4; ++j) {
            u128 s2 = (u128)m * c.n[j] + t[j] + carry;
            t[j - 1] = (u64)s2;
            carry = s2 >> 64;
        }
        s = (u128)t[4] + carry;
        t[3] = (u64)s;
        t[4] = t[5] + (u64)(s >> 64);
    }
    u64 r[4] = {t[0], t[1], t[2], t[3]};
    if (t[4] || geq(r, c.n)) sub(r, c.n);
    memcpy(out, r, sizeof(r));
}

void ctx_init(Ctx& c, const u64 n[4]) {
    memcpy(c.n, n, sizeof(c.n));
    // Newton iteration for n^-1 mod 2^64 (n odd), then negate.
    u64 inv = 1;
    for (int i = 0; i < 6; ++i) inv *= 2 - c.n[0] * inv;
    c.n0inv = (u64)(0 - inv);
    // R mod n by 256 doublings of 1; R^2 mod n by 256 more.
    u64 r[4] = {1, 0, 0, 0};
    for (int i = 0; i < 256; ++i) {
        u64 carry = r[3] >> 63;
        r[3] = (r[3] << 1) | (r[2] >> 63);
        r[2] = (r[2] << 1) | (r[1] >> 63);
        r[1] = (r[1] << 1) | (r[0] >> 63);
        r[0] <<= 1;
        if (carry || geq(r, c.n)) sub(r, c.n);
    }
    memcpy(c.one_m, r, sizeof(r));
    u64 r2[4];
    memcpy(r2, r, sizeof(r2));
    for (int i = 0; i < 256; ++i) {
        u64 carry = r2[3] >> 63;
        r2[3] = (r2[3] << 1) | (r2[2] >> 63);
        r2[2] = (r2[2] << 1) | (r2[1] >> 63);
        r2[1] = (r2[1] << 1) | (r2[0] >> 63);
        r2[0] <<= 1;
        if (carry || geq(r2, c.n)) sub(r2, c.n);
    }
    memcpy(c.r2, r2, sizeof(r2));
}

inline int exp_bit(const u64 e[4], int t) {
    return (int)((e[t >> 6] >> (t & 63)) & 1);
}

inline int exp_top_bit(const u64 e[4]) {
    for (int t = 255; t >= 0; --t)
        if (exp_bit(e, t)) return t;
    return -1;
}

// base^e mod n, 4-bit fixed window.
void mod_pow(const Ctx& c, const u64 base[4], const u64 e[4], u64 out[4]) {
    u64 table[16][4];
    memcpy(table[0], c.one_m, 32);
    mont_mul(c, base, c.r2, table[1]);  // to Montgomery
    for (int i = 2; i < 16; ++i) mont_mul(c, table[i - 1], table[1], table[i]);
    u64 acc[4];
    memcpy(acc, c.one_m, 32);
    int top = exp_top_bit(e);
    // start at the highest 4-aligned window covering bit `top`
    // (squaring Montgomery-one is a fixed point, so the first
    // window's four squarings are harmless)
    for (int w = (top < 0 ? -1 : top / 4); w >= 0; --w) {
        mont_mul(c, acc, acc, acc);
        mont_mul(c, acc, acc, acc);
        mont_mul(c, acc, acc, acc);
        mont_mul(c, acc, acc, acc);
        int idx = (exp_bit(e, 4 * w + 3) << 3) | (exp_bit(e, 4 * w + 2) << 2) |
                  (exp_bit(e, 4 * w + 1) << 1) | exp_bit(e, 4 * w);
        if (idx) mont_mul(c, acc, table[idx], acc);
    }
    u64 one[4] = {1, 0, 0, 0};
    mont_mul(c, acc, one, out);  // from Montgomery
}

// u1^e1 * u2^e2 mod n, Shamir's trick (the Chaum-Pedersen shape).
void dual_pow(const Ctx& c, const u64 u1[4], const u64 e1[4],
              const u64 u2[4], const u64 e2[4], u64 out[4]) {
    u64 t1[4], t2[4], t12[4];
    mont_mul(c, u1, c.r2, t1);
    mont_mul(c, u2, c.r2, t2);
    mont_mul(c, t1, t2, t12);
    u64 acc[4];
    memcpy(acc, c.one_m, 32);
    int top1 = exp_top_bit(e1), top2 = exp_top_bit(e2);
    int top = top1 > top2 ? top1 : top2;
    for (int t = top; t >= 0; --t) {
        mont_mul(c, acc, acc, acc);
        int idx = exp_bit(e1, t) | (exp_bit(e2, t) << 1);
        if (idx == 1) mont_mul(c, acc, t1, acc);
        else if (idx == 2) mont_mul(c, acc, t2, acc);
        else if (idx == 3) mont_mul(c, acc, t12, acc);
    }
    u64 one[4] = {1, 0, 0, 0};
    mont_mul(c, acc, one, out);
}

// Independent exponentiations parallelize trivially; threading kicks
// in above a batch-size floor where spawn cost (~20 us/thread)
// amortizes.  ctypes releases the GIL for the whole call.
constexpr int kParallelFloor = 64;

template <typename F>
void run_batch(int b, F&& body) {
    unsigned hw = std::thread::hardware_concurrency();
    int threads = (int)(hw ? hw : 1);
    if (threads > 16) threads = 16;
    if (b < kParallelFloor || threads <= 1) {
        body(0, b);
        return;
    }
    if (threads > b) threads = b;
    std::vector<std::thread> pool;
    int chunk = (b + threads - 1) / threads;
    for (int t = 0; t < threads; ++t) {
        int lo = t * chunk, hi = lo + chunk < b ? lo + chunk : b;
        if (lo >= hi) break;
        pool.emplace_back([&body, lo, hi] { body(lo, hi); });
    }
    for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// bases/exps/out: b rows of 32-byte little-endian values; mod: one
// 32-byte odd modulus shared by the whole batch.
void modpow256_batch(const uint8_t* bases, const uint8_t* exps,
                     const uint8_t* mod, uint8_t* out, int b) {
    Ctx c;
    u64 n[4];
    memcpy(n, mod, 32);
    ctx_init(c, n);
    run_batch(b, [&](int lo, int hi) {
        for (int i = lo; i < hi; ++i) {
            u64 base[4], e[4], r[4];
            memcpy(base, bases + 32 * i, 32);
            memcpy(e, exps + 32 * i, 32);
            mod_pow(c, base, e, r);
            memcpy(out + 32 * i, r, 32);
        }
    });
}

void dualpow256_batch(const uint8_t* u1, const uint8_t* e1,
                      const uint8_t* u2, const uint8_t* e2,
                      const uint8_t* mod, uint8_t* out, int b) {
    Ctx c;
    u64 n[4];
    memcpy(n, mod, 32);
    ctx_init(c, n);
    run_batch(b, [&](int lo, int hi) {
        for (int i = lo; i < hi; ++i) {
            u64 a[4], x[4], bb[4], y[4], r[4];
            memcpy(a, u1 + 32 * i, 32);
            memcpy(x, e1 + 32 * i, 32);
            memcpy(bb, u2 + 32 * i, 32);
            memcpy(y, e2 + 32 * i, 32);
            dual_pow(c, a, x, bb, y, r);
            memcpy(out + 32 * i, r, 32);
        }
    });
}

int modpow256_selftest() {
    // n = 1000003 (odd), 2^20 mod n = 48573
    uint8_t n[32] = {0}, base[32] = {0}, e[32] = {0}, out[32] = {0};
    u64 nn = 1000003;
    memcpy(n, &nn, 8);
    base[0] = 2;
    e[0] = 20;
    modpow256_batch(base, e, n, out, 1);
    u64 got;
    memcpy(&got, out, 8);
    if (got != 48573) return 1;
    // dual: 3^7 * 5^4 mod 1000003 = 2187 * 625 mod 1000003 = 1366875
    // mod 1000003 = 366872
    uint8_t u1[32] = {0}, e1[32] = {0}, u2[32] = {0}, e2[32] = {0};
    u1[0] = 3; e1[0] = 7; u2[0] = 5; e2[0] = 4;
    dualpow256_batch(u1, e1, u2, e2, n, out, 1);
    memcpy(&got, out, 8);
    if (got != 366872) return 2;
    // e = 0 -> 1
    memset(e, 0, 32);
    modpow256_batch(base, e, n, out, 1);
    memcpy(&got, out, 8);
    if (got != 1) return 3;
    return 0;
}

}  // extern "C"
