"""Native (C++) kernels, loaded via ctypes.

The shared library is compiled on demand with the system toolchain and
cached next to the sources (or in a per-user cache dir if the package
is read-only).  ``native_available()`` reports whether the toolchain
worked; selecting crypto_backend='cpp' without it is fail-fast
(CppErasureCoder raises) — callers that want degradation should check
``native_available()`` and choose 'cpu' themselves.
"""

from cleisthenes_tpu.native.build import load_gf256, native_available

__all__ = ["load_gf256", "native_available"]
