"""Native (C++) kernels, loaded via ctypes.

The shared library is compiled on demand with the system toolchain and
cached next to the sources (or in a per-user cache dir if the package
is read-only).  Everything degrades gracefully: if no compiler is
available the callers fall back to the numpy reference backend.
"""

from cleisthenes_tpu.native.build import load_gf256, native_available

__all__ = ["load_gf256", "native_available"]
