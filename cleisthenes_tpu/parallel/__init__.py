"""Multi-device parallelism for the crypto plane (SURVEY.md §2.2, §5.7-5.8)."""

from cleisthenes_tpu.parallel.mesh import CryptoMesh, make_crypto_mesh

__all__ = ["CryptoMesh", "make_crypto_mesh"]
