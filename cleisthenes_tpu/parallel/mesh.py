"""The crypto-plane device mesh: in-framework multi-chip sharding.

SURVEY.md §2.2/§5.7 pin the two parallel axes this framework owns:

- ``'v'`` — the validator/instance axis.  N concurrent RBC instances
  (one per proposer, reference docs/HONEYBADGER-EN.md:85-89,
  rbc/rbc.go:17) produce N independent tensor workloads per epoch;
  sharding the batch axis over 'v' is the data-parallel axis.
- ``'l'`` — the shard-length axis.  RS coding is GF(2)-linear along a
  shard's byte columns, so the length axis shards cleanly — the
  framework's sequence-parallel analogue (SURVEY.md §5.7: "shard the
  RS/Merkle/TPKE tensors along the shard-length axis across v5e
  cores").

Placement policy per kernel family:

- RS encode/decode (``ops.rs_xla``): 2-D ``P('v', None, 'l')`` — the
  contraction is over the k-shard axis, so both batch and length shard
  with zero collectives.
- Merkle forest / branch verify / modexp (``ops.sha256_xla``,
  ``ops.modmath``): hashing and exponentiation are sequential *within*
  an element but independent *across* the batch, so the batch axis
  shards over ALL devices flat: ``P(('v','l'))``.

XLA's GSPMD does the partitioning: we place the inputs with
``jax.device_put`` under a ``NamedSharding`` and call the exact same
jitted kernels; resharding between the RS layout and the flat layout
is the compiler-inserted ICI collective (the all-gather the
``__graft_entry__`` dry run demonstrates).

Everything works identically on the 8-virtual-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) that tests
and the driver's ``dryrun_multichip`` use — no TPU needed to exercise
the sharding paths.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def validate_mesh_shape(mesh_shape) -> Tuple[int, int]:
    """Normalize/validate a (v, l) mesh shape (shared by Config and
    CryptoMesh so both layers accept exactly the same shapes).
    Importable without jax."""
    ms = tuple(mesh_shape)
    # bool is an int subclass: (True, True) must not validate as (1, 1)
    if len(ms) != 2 or any(
        isinstance(d, bool) or (not isinstance(d, int)) or d < 1 for d in ms
    ):
        raise ValueError(
            f"mesh_shape must be two positive ints (v, l), got {mesh_shape!r}"
        )
    return ms


class CryptoMesh:
    """A ('v', 'l') jax.sharding.Mesh plus the placement helpers the
    crypto plane uses.

    ``mesh_shape=(v, l)`` is ``Config.mesh_shape``; devices default to
    ``jax.devices()`` (the first v*l of them).
    """

    def __init__(
        self, mesh_shape: Tuple[int, int], devices: Optional[Sequence] = None
    ):
        import jax
        from jax.sharding import Mesh

        v, l = validate_mesh_shape(mesh_shape)
        if devices is None:
            devices = jax.devices()
        if len(devices) < v * l:
            raise ValueError(
                f"mesh {mesh_shape} needs {v * l} devices, "
                f"have {len(devices)}"
            )
        self.shape = (v, l)
        self.n_devices = v * l
        self.mesh = Mesh(
            np.asarray(devices[: v * l]).reshape(v, l), ("v", "l")
        )

    # -- shardings ---------------------------------------------------------

    def _sharding(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, spec)

    def spec_vl(self, ndim: int):
        """P('v', None, ..., 'l'): batch over 'v', last axis over 'l'
        (the RS-codec layout)."""
        from jax.sharding import PartitionSpec as P

        return self._sharding(P("v", *([None] * (ndim - 2)), "l"))

    def spec_v(self, ndim: int):
        """P('v', None, ...): batch over 'v' only, replicated over 'l'
        (per-instance matrices whose trailing axes are contractions)."""
        from jax.sharding import PartitionSpec as P

        return self._sharding(P("v", *([None] * (ndim - 1))))

    def spec_flat(self, ndim: int):
        """P(('v','l'), None, ...): batch axis over every device (the
        hash/modexp layout)."""
        from jax.sharding import PartitionSpec as P

        return self._sharding(P(("v", "l"), *([None] * (ndim - 1))))

    # -- placement ---------------------------------------------------------

    def put_vl(self, x):
        """Place an array batch-over-'v', length-over-'l'."""
        import jax

        return jax.device_put(x, self.spec_vl(np.ndim(x)))

    def put_v(self, x):
        """Place an array batch-over-'v', everything else replicated."""
        import jax

        return jax.device_put(x, self.spec_v(np.ndim(x)))

    def put_flat(self, *arrays):
        """Place arrays with the batch axis sharded over all devices.
        Returns a tuple matching the inputs."""
        import jax

        return tuple(
            jax.device_put(a, self.spec_flat(np.ndim(a))) for a in arrays
        )

    # -- batch padding -----------------------------------------------------

    @staticmethod
    def pad_rows(a: np.ndarray, multiple: int) -> Tuple[np.ndarray, int]:
        """Pad axis 0 up to a multiple by repeating row 0 (valid data,
        so padded lanes execute the same math); returns (padded,
        original_len)."""
        b = a.shape[0]
        pad = (-b) % multiple
        if pad:
            a = np.concatenate([a, np.repeat(a[:1], pad, axis=0)])
        return a, b

    @staticmethod
    def pad_cols(a: np.ndarray, multiple: int) -> Tuple[np.ndarray, int]:
        """Zero-pad the LAST axis up to a multiple; returns (padded,
        original_len).  Used for the 'l' (shard-length) axis, where
        byte columns are independent under GF coding."""
        l = a.shape[-1]
        pad = (-l) % multiple
        if pad:
            widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
            a = np.pad(a, widths)
        return a, l


def make_crypto_mesh(
    mesh_shape: Optional[Tuple[int, int]],
    devices: Optional[Sequence] = None,
) -> Optional[CryptoMesh]:
    """None-passthrough constructor (mesh_shape=None = single-device)."""
    if mesh_shape is None:
        return None
    return CryptoMesh(tuple(mesh_shape), devices)


__all__ = ["CryptoMesh", "make_crypto_mesh", "validate_mesh_shape"]
