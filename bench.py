"""Benchmarks: north-star crypto plane + real-protocol epoch.

Two measurements, one JSON line (the driver contract):

1. **Crypto plane @ north star** (primary metric): wall-clock p50 of
   ONE HBBFT epoch's hot-path crypto at BASELINE north-star scale —
   N=128, f=42, 10k-tx batch — 'tpu' backend vs the CPU reference
   path.  Work per epoch (docs/HONEYBADGER-EN.md:93-96 cost model):
     - RS-encode every validator's proposal into N shards  [N encodes]
     - build the Merkle forest over all N shard sets       [N trees]
     - verify the N^2 ECHO-phase Merkle branches           [N^2 proofs]
     - RS-decode N proposals from K surviving shards       [N decodes]
     - verify N^2 threshold-decryption shares              [N^2 CP]

2. **Real protocol @ N=16** (VERDICT round-1 item 3's criterion): full
   HBBFT epochs over the in-proc ChannelNetwork — every message
   crossing the wire codec and MAC layer, all crypto routed through
   the CryptoHub's batched dispatches — 'tpu' vs 'cpu' backend.

Output (ONE line):
  {"metric": "epoch_crypto_p50_n128_f42_b10k", "value": p50_ms,
   "unit": "ms", "vs_baseline": cpu_p50/tpu_p50,
   "protocol_n16": {...}, ...}

``vs_baseline`` > 1 means the TPU path beats the CPU reference.
Comparator note: the CPU reference uses the native C++ GF backend when
it builds (honest erasure-coding baseline); its modexp baseline is
python pow() — flagged in ``baseline_note`` since a production Go path
would use an optimized bignum library.
"""

import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np

# ---- north-star crypto-plane config (BASELINE.json) ----
N = 128
F = 42
K = N - 2 * F  # 44 data shards
BATCH_TXS = 10_000
TX_BYTES = 64
ITERS = 3
SHARE_VERIFY_CHUNK = 4096  # CP checks per dispatch (2 dual-pows each)

# ---- real-protocol config (BASELINE config 2 shape) ----
PROTO_N = 16
PROTO_BATCH = 1024
PROTO_EPOCHS = 3


def payload_bytes() -> int:
    # each validator proposes B/N txs (docs/HONEYBADGER-EN.md:51-56)
    return (BATCH_TXS // N) * TX_BYTES


def epoch_crypto(backend: str, rng: np.random.Generator) -> float:
    """One north-star epoch's batched crypto plane; returns seconds."""
    from cleisthenes_tpu.ops.backend import BatchCrypto
    from cleisthenes_tpu.ops.payload import split_payload
    from cleisthenes_tpu.ops import tpke as tpke_mod

    crypto = BatchCrypto(backend, N, F, K)

    # --- prepare inputs (not timed) ---
    proposals = [
        rng.integers(0, 256, size=payload_bytes(), dtype=np.uint8).tobytes()
        for _ in range(N)
    ]
    data = np.stack([split_payload(p, K) for p in proposals])  # (N, K, L)

    pub, secrets_ = tpke_mod.deal(N, F + 1, seed=123)
    ct = tpke_mod.Tpke(pub).encrypt(b"epoch-key-material")
    ctx = b"bench-ctx"
    shares = [
        tpke_mod.issue_share(secrets_[i % N], ct.c1, ctx) for i in range(N)
    ]

    t0 = time.perf_counter()

    # RS encode all N proposals -> (N, n, L)
    encoded = crypto.erasure.encode_batch(data)

    # Merkle forest: one tree per proposal
    trees = crypto.merkle.build_batch(encoded)

    # ECHO-phase branch verification: N branches per instance = N^2
    roots = np.stack(
        [np.frombuffer(t.root, dtype=np.uint8) for t in trees]
    ).repeat(N, axis=0)
    leaves = encoded.reshape(N * N, -1)
    depth = trees[0].depth
    branches = np.stack(
        [
            np.stack([np.frombuffer(s, dtype=np.uint8) for s in t.branch(j)])
            for t in trees
            for j in range(N)
        ]
    ).reshape(N * N, depth, 32)
    indices = np.tile(np.arange(N), N)
    ok = crypto.merkle.verify_batch(roots, leaves, branches, indices)
    assert bool(ok.all())

    # RS decode: reconstruct each proposal from K surviving shards
    # (the worst-case parity-heavy survivor set)
    survivor_idx = np.arange(N - K, N)
    dec = crypto.erasure.decode_batch(
        np.tile(survivor_idx, (N, 1)),
        encoded[:, survivor_idx, :],
    )
    assert dec.shape == data.shape

    # TPKE share verification: N shares per ciphertext x N ciphertexts,
    # batched through the ModEngine in fixed-size dispatches
    all_shares = shares * N  # N^2 CP proofs
    engine_backend = "cpu" if backend == "cpp" else backend
    for off in range(0, len(all_shares), SHARE_VERIFY_CHUNK):
        res = tpke_mod.verify_shares(
            pub,
            ct.c1,
            all_shares[off : off + SHARE_VERIFY_CHUNK],
            ctx,
            backend=engine_backend,
        )
        assert all(res)

    return time.perf_counter() - t0


def measure_crypto(backend: str) -> float:
    rng = np.random.default_rng(7)
    epoch_crypto(backend, rng)  # warm-up (jit compile)
    times = [epoch_crypto(backend, rng) for _ in range(ITERS)]
    return statistics.median(times)


def cpu_reference_backend() -> str:
    """Honest CPU comparator: the native C++ GF kernels when they
    build, else the numpy reference."""
    try:
        from cleisthenes_tpu.ops.rs_cpp import CppErasureCoder  # noqa: F401

        CppErasureCoder(4, 2)  # forces the compile
        return "cpp"
    except Exception:
        return "cpu"


# ---------------------------------------------------------------------------
# real-protocol benchmark: full HBBFT epochs over the channel transport
# ---------------------------------------------------------------------------


def build_network(backend: str):
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.honeybadger import HoneyBadger, setup_keys
    from cleisthenes_tpu.transport.base import HmacAuthenticator
    from cleisthenes_tpu.transport.broadcast import ChannelBroadcaster
    from cleisthenes_tpu.transport.channel import ChannelNetwork

    cfg = Config(
        n=PROTO_N,
        batch_size=PROTO_BATCH,
        crypto_backend=backend,
        seed=99,
    )
    ids = [f"node{i:02d}" for i in range(PROTO_N)]
    keys = setup_keys(cfg, ids, seed=77)
    net = ChannelNetwork()
    nodes = {}
    for nid in ids:
        hb = HoneyBadger(
            config=cfg,
            node_id=nid,
            member_ids=ids,
            keys=keys[nid],
            out=ChannelBroadcaster(net, nid, ids),
            auto_propose=False,  # manual epoch stepping for timing
        )
        nodes[nid] = hb
        net.join(nid, hb, HmacAuthenticator(nid, keys[nid].mac_keys))
    return cfg, net, nodes


def measure_protocol(backend: str) -> dict:
    """PROTO_EPOCHS full epochs; per-epoch wall clock + tx/sec."""
    cfg, net, nodes = build_network(backend)
    rng = np.random.default_rng(13)
    total_txs = PROTO_BATCH * PROTO_EPOCHS
    node_ids = sorted(nodes)
    for i in range(total_txs):
        tx = rng.integers(0, 256, size=TX_BYTES, dtype=np.uint8).tobytes()
        nodes[node_ids[i % PROTO_N]].add_transaction(tx)

    # warm-up epoch (jit compile on the tpu backend)
    for hb in nodes.values():
        hb.start_epoch()
    net.run()

    epoch_times = []
    committed = 0
    for _ in range(PROTO_EPOCHS):
        if all(hb.pending_tx_count() == 0 for hb in nodes.values()):
            break
        before = len(next(iter(nodes.values())).committed_batches)
        t0 = time.perf_counter()
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        epoch_times.append(time.perf_counter() - t0)
        after = len(next(iter(nodes.values())).committed_batches)
        committed += sum(
            len(b)
            for b in next(iter(nodes.values())).committed_batches[before:after]
        )
    # agreement sanity: every node committed the identical history
    histories = {
        tuple(tuple(sorted(b.tx_list())) for b in hb.committed_batches)
        for hb in nodes.values()
    }
    assert len(histories) == 1, "protocol benchmark broke agreement"
    p50 = statistics.median(epoch_times) if epoch_times else float("nan")
    dispatches = statistics.median(
        [hb.hub.stats()["dispatches"] for hb in nodes.values()]
    )
    return {
        "epoch_p50_ms": round(p50 * 1000.0, 3),
        "tx_per_sec": round(committed / sum(epoch_times), 1)
        if epoch_times
        else None,
        "hub_dispatches_per_node": int(dispatches),
    }


# ---------------------------------------------------------------------------
# harness: subprocess isolation + relay probing + guaranteed JSON output
# ---------------------------------------------------------------------------


def run_child() -> None:
    """The actual measurement; prints the JSON result line.

    Runs in a subprocess so a hung TPU relay (which cannot be
    interrupted in-process) is bounded by the parent's timeout.
    """
    cpu_ref = cpu_reference_backend()
    accel_p50 = measure_crypto("tpu")
    cpu_p50 = measure_crypto(cpu_ref)
    proto_tpu = measure_protocol("tpu")
    proto_cpu = measure_protocol(cpu_ref)
    print(
        json.dumps(
            {
                "metric": "epoch_crypto_p50_n128_f42_b10k",
                "value": round(accel_p50 * 1000.0, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_p50 / accel_p50, 3),
                "cpu_reference": cpu_ref,
                "baseline_note": (
                    "CPU GF plane uses native C++ kernels when available; "
                    "CPU modexp baseline is python pow()"
                ),
                "protocol_n16": {
                    "n": PROTO_N,
                    "batch": PROTO_BATCH,
                    "tpu": proto_tpu,
                    "cpu": proto_cpu,
                    "vs_cpu": round(
                        proto_cpu["epoch_p50_ms"] / proto_tpu["epoch_p50_ms"],
                        3,
                    )
                    if proto_tpu["epoch_p50_ms"]
                    else None,
                },
            }
        )
    )


CHILD_TIMEOUT_S = int(os.environ.get("BENCH_CHILD_TIMEOUT_S", "900"))


def _spawn_child(force_cpu: bool) -> "tuple[dict | None, str]":
    """Run the measurement subprocess; return (parsed JSON, detail)."""
    env = dict(os.environ)
    if force_cpu:
        # skip the axon PJRT plugin registration entirely so the dead
        # relay is never touched; the XLA path then runs on host CPU
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            timeout=CHILD_TIMEOUT_S,
            capture_output=True,
            text=True,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {CHILD_TIMEOUT_S}s"
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                return parsed, ""
        except json.JSONDecodeError:
            continue
    tail = (r.stderr or r.stdout or "").strip().splitlines()
    return None, f"rc={r.returncode}: {' | '.join(tail[-3:]) or 'no output'}"


def _probe_relay(timeout_s: int = 90) -> bool:
    """Cheap subprocess probe: can the default backend run one op?

    A dead axon relay hangs indefinitely on first dispatch, so the
    probe (not the full measurement) is what bounds the cost of
    discovering an outage.
    """
    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "print('PROBE_OK' if float(np.asarray(jnp.ones(8).sum())) == 8.0"
        " else 'PROBE_BAD')"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PROBE_OK" in r.stdout


def main() -> None:
    """Driver entry: bounded retry on the TPU relay, CPU-XLA fallback,
    and ALWAYS one parseable JSON line on stdout (never a bare
    traceback — the round-1 failure mode, BENCH_r01.json rc=1)."""
    errors = []
    healthy = False
    for attempt in range(2):
        if _probe_relay():
            healthy = True
            break
        errors.append(f"probe {attempt + 1}: relay unreachable")
        time.sleep(5)
    if healthy:
        result, detail = _spawn_child(force_cpu=False)
        if result is not None:
            print(json.dumps(result))
            return
        errors.append(f"tpu run: {detail}")
    result, detail = _spawn_child(force_cpu=True)
    if result is not None:
        result["note"] = (
            "axon TPU relay unavailable; XLA path measured on host CPU "
            f"({'; '.join(errors)})"
        )
        print(json.dumps(result))
        return
    errors.append(f"cpu fallback: {detail}")
    print(
        json.dumps(
            {
                "metric": "epoch_crypto_p50_n128_f42_b10k",
                "value": None,
                "unit": "ms",
                "vs_baseline": None,
                "error": "; ".join(errors),
            }
        )
    )


if __name__ == "__main__":
    if "--child" in sys.argv:
        run_child()
    else:
        main()
