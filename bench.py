"""Per-epoch crypto-plane benchmark (the BASELINE.json metric).

Measures the wall-clock p50 of ONE HBBFT epoch's worth of hot-path
crypto at BASELINE config 3 scale — N=64, f=21, 10k-tx batch — on the
TPU backend, against the same work on the pure-CPU reference backend
(the stand-in for the reference's pure-Go path, which publishes no
numbers of its own; BASELINE.md "published: {}").

One epoch's crypto (docs/HONEYBADGER-EN.md:93-96 cost model):
  - RS-encode every validator's proposal into N shards       [N encodes]
  - build the Merkle forest over all N shard sets            [N trees]
  - verify the N^2 ECHO-phase Merkle branches                [N^2 proofs]
  - RS-decode N proposals from K surviving shards            [N decodes]
  - verify N^2 threshold-decryption shares (N per ciphertext)[N^2 CP checks]

Prints ONE JSON line:
  {"metric": ..., "value": p50_ms, "unit": "ms", "vs_baseline": cpu/tpu}

``vs_baseline`` > 1 means the TPU crypto plane beats the CPU reference
path; the north-star target is the whole epoch under 1000 ms.
"""

import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np

N = 64
F = 21
K = N - 2 * F  # 22 data shards
BATCH_TXS = 10_000
TX_BYTES = 64
ITERS = 5
SHARE_VERIFY_CHUNK = 4096  # CP checks per dispatch (2 dual-pows each)


def payload_bytes() -> int:
    # each validator proposes B/N txs (docs/HONEYBADGER-EN.md:51-56)
    return (BATCH_TXS // N) * TX_BYTES


def epoch_crypto(backend: str, rng: np.random.Generator) -> float:
    """One epoch's batched crypto plane; returns seconds."""
    from cleisthenes_tpu.ops.backend import BatchCrypto
    from cleisthenes_tpu.ops.payload import split_payload
    from cleisthenes_tpu.ops import tpke as tpke_mod

    crypto = BatchCrypto(backend, N, F, K)

    # --- prepare inputs (not timed) ---
    proposals = [
        rng.integers(0, 256, size=payload_bytes(), dtype=np.uint8).tobytes()
        for _ in range(N)
    ]
    data = np.stack([split_payload(p, K) for p in proposals])  # (N, K, L)

    pub, secrets_ = tpke_mod.deal(N, F + 1, seed=123)
    ct = tpke_mod.Tpke(pub).encrypt(b"epoch-key-material")
    ctx = b"bench-ctx"
    shares = [
        tpke_mod.issue_share(secrets_[i % N], ct.c1, ctx) for i in range(N)
    ]

    t0 = time.perf_counter()

    # RS encode all N proposals -> (N, n, L)
    encoded = crypto.erasure.encode_batch(data)

    # Merkle forest: one tree per proposal
    trees = crypto.merkle.build_batch(encoded)

    # ECHO-phase branch verification: N branches per instance = N^2
    roots = np.stack(
        [np.frombuffer(t.root, dtype=np.uint8) for t in trees]
    ).repeat(N, axis=0)
    leaves = encoded.reshape(N * N, -1)
    depth = trees[0].depth
    branches = np.stack(
        [
            np.stack([np.frombuffer(s, dtype=np.uint8) for s in t.branch(j)])
            for t in trees
            for j in range(N)
        ]
    ).reshape(N * N, depth, 32)
    indices = np.tile(np.arange(N), N)
    ok = crypto.merkle.verify_batch(roots, leaves, branches, indices)
    assert bool(ok.all())

    # RS decode: reconstruct each proposal from K surviving shards
    # (the worst-case parity-heavy survivor set)
    survivor_idx = np.arange(N - K, N)
    dec = crypto.erasure.decode_batch(
        np.tile(survivor_idx, (N, 1)),
        encoded[:, survivor_idx, :],
    )
    assert dec.shape == data.shape

    # TPKE share verification: N shares per ciphertext x N ciphertexts,
    # batched through the ModEngine in fixed-size dispatches
    all_shares = shares * N  # N^2 CP proofs
    for off in range(0, len(all_shares), SHARE_VERIFY_CHUNK):
        res = tpke_mod.verify_shares(
            pub,
            ct.c1,
            all_shares[off : off + SHARE_VERIFY_CHUNK],
            ctx,
            backend=backend,
        )
        assert all(res)

    return time.perf_counter() - t0


def measure(backend: str) -> float:
    rng = np.random.default_rng(7)
    epoch_crypto(backend, rng)  # warm-up (jit compile)
    times = [epoch_crypto(backend, rng) for _ in range(ITERS)]
    return statistics.median(times)


def run_child() -> None:
    """The actual measurement; prints the JSON result line.

    Runs in a subprocess so a hung TPU relay (which cannot be
    interrupted in-process) is bounded by the parent's timeout.
    """
    # the accelerated path under test ('tpu' = XLA on whatever device
    # is present; on a CPU-only host it still exercises the XLA path)
    accel_p50 = measure("tpu")
    # the pure-CPU reference path (numpy GF tables + python modexp)
    cpu_p50 = measure("cpu")
    print(
        json.dumps(
            {
                "metric": "epoch_crypto_p50_n64_f21_b10k",
                "value": round(accel_p50 * 1000.0, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_p50 / accel_p50, 3),
            }
        )
    )


CHILD_TIMEOUT_S = int(os.environ.get("BENCH_CHILD_TIMEOUT_S", "900"))


def _spawn_child(force_cpu: bool) -> "tuple[dict | None, str]":
    """Run the measurement subprocess; return (parsed JSON, detail)."""
    env = dict(os.environ)
    if force_cpu:
        # skip the axon PJRT plugin registration entirely so the dead
        # relay is never touched; the XLA path then runs on host CPU
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            timeout=CHILD_TIMEOUT_S,
            capture_output=True,
            text=True,
            env=env,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {CHILD_TIMEOUT_S}s"
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                return parsed, ""
        except json.JSONDecodeError:
            continue
    tail = (r.stderr or r.stdout or "").strip().splitlines()
    return None, f"rc={r.returncode}: {' | '.join(tail[-3:]) or 'no output'}"


def _probe_relay(timeout_s: int = 90) -> bool:
    """Cheap subprocess probe: can the default backend run one op?

    A dead axon relay hangs indefinitely on first dispatch, so the
    probe (not the full 15-min measurement) is what bounds the cost of
    discovering an outage.
    """
    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "print('PROBE_OK' if float(np.asarray(jnp.ones(8).sum())) == 8.0"
        " else 'PROBE_BAD')"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PROBE_OK" in r.stdout


def main() -> None:
    """Driver entry: bounded retry on the TPU relay, CPU-XLA fallback,
    and ALWAYS one parseable JSON line on stdout (never a bare
    traceback — the round-1 failure mode, BENCH_r01.json rc=1)."""
    errors = []
    healthy = False
    for attempt in range(2):
        if _probe_relay():
            healthy = True
            break
        errors.append(f"probe {attempt + 1}: relay unreachable")
        time.sleep(5)
    if healthy:
        result, detail = _spawn_child(force_cpu=False)
        if result is not None:
            print(json.dumps(result))
            return
        errors.append(f"tpu run: {detail}")
    result, detail = _spawn_child(force_cpu=True)
    if result is not None:
        result["note"] = (
            "axon TPU relay unavailable; XLA path measured on host CPU "
            f"({'; '.join(errors)})"
        )
        print(json.dumps(result))
        return
    errors.append(f"cpu fallback: {detail}")
    print(
        json.dumps(
            {
                "metric": "epoch_crypto_p50_n64_f21_b10k",
                "value": None,
                "unit": "ms",
                "vs_baseline": None,
                "error": "; ".join(errors),
            }
        )
    )


if __name__ == "__main__":
    if "--child" in sys.argv:
        run_child()
    else:
        main()
