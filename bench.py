"""Benchmarks: north-star crypto plane + real-protocol epochs.

Sections, one JSON line total (the driver contract):

1. **Crypto plane @ north star** (primary metric): wall-clock p50 of
   ONE HBBFT epoch's hot-path crypto at BASELINE north-star scale —
   N=128, f=42, 10k-tx batch — 'tpu' backend vs the CPU reference
   path.  Work per epoch (docs/HONEYBADGER-EN.md:93-96 cost model):
     - RS-encode every validator's proposal into N shards  [N encodes]
     - build the Merkle forest over all N shard sets       [N trees]
     - verify the N^2 ECHO-phase Merkle branches           [N^2 proofs]
     - RS-decode N proposals from K surviving shards       [N decodes]
     - verify N^2 threshold-decryption shares              [N^2 CP]

2. **Real protocol @ N=16 and N=64** (BASELINE primary metric "tx/sec
   & epoch p50 at N=64/128"): full HBBFT epochs over the in-proc
   ChannelNetwork — every message crossing the wire codec and MAC
   layer, crypto routed through the CryptoHub's wave-batched
   dispatches — 'tpu' vs 'cpu' backend.  Warm-up epochs consume their
   own transactions; measured epochs are guaranteed PROTO_EPOCHS.

3. **Order-then-settle overlap** (ISSUE 8): chained real-protocol
   epochs through the two-frontier commit split
   (Config.order_then_settle) vs the coupled arm on the identical
   seeded workload — ``pipeline_overlap_x`` is serial epoch walls /
   elapsed wall, so > 1.0 certifies epoch e+1's RBC/BBA genuinely ran
   under epoch e's trailing decryption.  (Replaces the retired
   crypto_n512_pipelined software-pipeline section, whose ~0.95
   "overlap" measured one dispatch queue against itself.)

4. **Same-box interleaved A/B** (``--ab BASE_REF``): HEAD vs a named
   git ref run alternately in one harness lifetime with paired
   deltas (tools/abench.py) — cross-box BENCH_* comparisons do not
   reproduce (WAVE_EVIDENCE.md), paired same-box runs do.

``platform`` records where the XLA path actually ran ('axon' = real
TPU via the relay, 'cpu' = XLA-on-host fallback) so every recorded
number self-documents its provenance (VERDICT round-2 item 5).

``vs_baseline`` > 1 means the accelerated path beats the CPU
reference.  Comparator note: the CPU reference uses the native C++ GF
kernels when they build AND the native C++ Montgomery modexp kernel
(native/modpow256.cpp, ~12us per 256-bit exponentiation) — an honest
optimized-native baseline, not python pow() (VERDICT round-2 item 7).
"""

import json
import math
import os
import statistics
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from tools import benchlock  # noqa: E402


def _append_trend(result: dict) -> None:
    """Fold the finished artifact into the perf-regression trend
    (BENCH_TREND.jsonl; tools/perfgate.py gates CI against it).
    Best-effort: trend bookkeeping must never sink a measurement."""
    try:
        from tools import perfgate

        perfgate.append_bench_trend(result)
    except Exception as exc:  # noqa: BLE001 — recorded, not raised
        print(f"[bench] trend append failed: {exc!r}", file=sys.stderr)


def _load_snapshot() -> dict:
    try:
        return benchlock.load_snapshot()
    except Exception:  # provenance must never sink a measurement
        return {"error": "load_snapshot failed"}


# ---- north-star crypto-plane config (BASELINE.json) ----
N = 128
F = 42
K = N - 2 * F  # 44 data shards
BATCH_TXS = 10_000
TX_BYTES = 64
ITERS = 3
# CP checks per dispatch (2 dual-pows each): the full N^2 = 16,384
# checks of the north-star epoch in ONE dispatch — chunking at 4096
# spent 3 extra relay round-trips (~0.12 s) for no compute benefit
SHARE_VERIFY_CHUNK = 16384

# ---- real-protocol configs ----
PROTO_EPOCHS = 3
PROTO_CONFIGS = {
    "protocol_n16": {"n": 16, "batch": 1024, "epochs": PROTO_EPOCHS},
    "protocol_n64": {"n": 64, "batch": 1024, "epochs": 2},
    # the paper's batch-amortization claim on the REAL path
    # (docs/HONEYBADGER-EN.md:110-113: tx-independent cost dominates
    # at B=1024; by B=16384 the RS/Merkle cost does): measured 10x
    # the tx/sec of the B=1024 row at ~1.5x the epoch latency
    "protocol_n64_b16k": {"n": 64, "batch": 16_384, "epochs": 1},
}
# BASELINE config 4 on the real message-passing path: ~130 s/epoch on
# one core (the whole 128-node cluster serialized in one process), so
# opt-in via BENCH_FULL=1; the default run carries this scale via the
# lockstep section (protocol_spmd_n128) and the crypto-plane metric.
if os.environ.get("BENCH_FULL") == "1":
    PROTO_CONFIGS["protocol_n128"] = {"n": 128, "batch": 2048, "epochs": 1}

# ---- order-then-settle overlap section (ISSUE 8) ----
# The retired crypto_n512_pipelined section measured a SOFTWARE
# pipeline over one dispatch queue (overlap_x ~0.95 — sequential was
# as fast as "pipelined").  pipeline_overlap_x now means what its
# name says: real protocol epochs chained through the two-frontier
# commit split, epoch e+1's RBC/BBA overlapping epoch e's trailing
# decryption, measured as sum(per-epoch propose->settle walls) over
# the elapsed wall (> 1.0 = epochs genuinely overlapped).
OVERLAP_N = 16
OVERLAP_BATCH = 512
OVERLAP_EPOCHS = 4


def payload_bytes(n: int = N, batch: int = BATCH_TXS) -> int:
    # each validator proposes B/N txs (docs/HONEYBADGER-EN.md:51-56)
    return max(batch // n, 1) * TX_BYTES


def epoch_crypto(backend: str, rng: np.random.Generator) -> float:
    """One north-star epoch's batched crypto plane; returns seconds."""
    from cleisthenes_tpu.ops.backend import BatchCrypto
    from cleisthenes_tpu.ops.payload import split_payload
    from cleisthenes_tpu.ops import tpke as tpke_mod

    crypto = BatchCrypto(backend, N, F, K)

    # --- prepare inputs (not timed) ---
    proposals = [
        rng.integers(0, 256, size=payload_bytes(), dtype=np.uint8).tobytes()
        for _ in range(N)
    ]
    data = np.stack([split_payload(p, K) for p in proposals])  # (N, K, L)

    pub, secrets_ = tpke_mod.deal(N, F + 1, seed=123)
    ct = tpke_mod.Tpke(pub).encrypt(b"epoch-key-material")
    ctx = b"bench-ctx"
    shares = [
        tpke_mod.issue_share(secrets_[i % N], ct.c1, ctx) for i in range(N)
    ]

    t0 = time.perf_counter()

    # RS encode all N proposals -> (N, n, L)
    encoded = crypto.erasure.encode_batch(data)

    # Merkle forest: one tree per proposal
    trees = crypto.merkle.build_batch(encoded)

    # ECHO-phase branch verification: N branches per instance = N^2
    roots = np.stack(
        [np.frombuffer(t.root, dtype=np.uint8) for t in trees]
    ).repeat(N, axis=0)
    leaves = encoded.reshape(N * N, -1)
    depth = trees[0].depth
    branches = np.stack(
        [
            np.stack([np.frombuffer(s, dtype=np.uint8) for s in t.branch(j)])
            for t in trees
            for j in range(N)
        ]
    ).reshape(N * N, depth, 32)
    indices = np.tile(np.arange(N), N)
    ok = crypto.merkle.verify_batch(roots, leaves, branches, indices)
    assert bool(ok.all())

    # RS decode: reconstruct each proposal from K surviving shards
    # (the worst-case parity-heavy survivor set)
    survivor_idx = np.arange(N - K, N)
    dec = crypto.erasure.decode_batch(
        np.tile(survivor_idx, (N, 1)),
        encoded[:, survivor_idx, :],
    )
    assert dec.shape == data.shape

    # TPKE share verification: N shares per ciphertext x N ciphertexts,
    # batched through the ModEngine in fixed-size dispatches
    all_shares = shares * N  # N^2 CP proofs
    engine_backend = "cpu" if backend == "cpp" else backend
    for off in range(0, len(all_shares), SHARE_VERIFY_CHUNK):
        res = tpke_mod.verify_shares(
            pub,
            ct.c1,
            all_shares[off : off + SHARE_VERIFY_CHUNK],
            ctx,
            backend=engine_backend,
        )
        assert all(res)

    return time.perf_counter() - t0


def measure_crypto(backend: str) -> float:
    rng = np.random.default_rng(7)
    epoch_crypto(backend, rng)  # warm-up (jit compile)
    times = [epoch_crypto(backend, rng) for _ in range(ITERS)]
    return statistics.median(times)


def cpu_reference_backend() -> str:
    """Honest CPU comparator: the native C++ GF kernels when they
    build, else the numpy reference.  (The modexp comparator is the
    native C++ Montgomery kernel either way — ops/modmath.py routes
    the 'cpu' ModEngine through it.)"""
    try:
        from cleisthenes_tpu.ops.rs_cpp import CppErasureCoder  # noqa: F401

        CppErasureCoder(4, 2)  # forces the compile
        return "cpp"
    except Exception:
        return "cpu"


def modexp_comparator_note() -> str:
    from cleisthenes_tpu.native.build import load_modpow

    if load_modpow() is not None:
        return (
            "CPU modexp baseline: native C++ Montgomery kernel "
            "(native/modpow256.cpp, ~12us/exp)"
        )
    return "CPU modexp baseline: python pow() (native kernel unavailable)"


# ---------------------------------------------------------------------------
# real-protocol benchmark: full HBBFT epochs over the channel transport
# ---------------------------------------------------------------------------


def build_network(
    backend: str, n: int = 16, batch: int = 1024, trace: bool = False
):
    """An in-proc cluster with the shared (cluster-batched) hub — see
    protocol.cluster.SimulatedCluster; manual epoch stepping."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    cfg = Config(
        n=n, batch_size=batch, crypto_backend=backend, seed=99, trace=trace
    )
    cluster = SimulatedCluster(
        config=cfg, key_seed=77, auto_propose=False, shared_hub=True
    )
    return cfg, cluster.net, cluster.nodes, cluster


def two_frontier_keys(metrics) -> dict:
    """The two-frontier per-epoch latencies every protocol section
    reports (ISSUE 8): propose -> ciphertext-ordered commit (what the
    application's ordering sees), propose -> settled plaintext, and
    the trailing decrypt lag's p95.  None on the coupled arm.
    perfgate/abench key on these exact names."""
    return {
        key: round(val * 1000.0, 3) if val is not None else None
        for key, val in (
            ("ordered_epoch_p50_ms", metrics.ordered_latency.p50),
            ("settled_epoch_p50_ms", metrics.epoch_latency.p50),
            ("decrypt_lag_p95_ms", metrics.settle_lag_latency.p95),
        )
    }


def measure_protocol(
    backend: str,
    n: int,
    batch: int,
    epochs: int,
    trace: bool = False,
    trace_out: "str | None" = None,
) -> dict:
    """``epochs`` measured full epochs (plus one untimed warm-up epoch
    with its OWN transactions, so warm-up never eats measured work —
    VERDICT round-2 item 8).  With ``trace=True`` the cluster runs
    under the flight recorder and the result carries a per-stage
    breakdown of epoch wall time (``stage_shares``) next to
    ``epoch_p50_ms`` — the instrument that makes a BENCH_* number
    explain itself (ISSUE 3)."""
    cfg, net, nodes, cluster = build_network(
        backend, n=n, batch=batch, trace=trace
    )
    rng = np.random.default_rng(13)
    node_ids = sorted(nodes)
    total_txs = batch * (epochs + 1)  # +1: the warm-up epoch's own txs
    for i in range(total_txs):
        tx = rng.integers(0, 256, size=TX_BYTES, dtype=np.uint8).tobytes()
        nodes[node_ids[i % n]].add_transaction(tx)

    # warm-up epoch (jit compile on the tpu backend)
    for hb in nodes.values():
        hb.start_epoch()
    net.run()

    epoch_times = []
    committed = 0
    for _ in range(epochs):
        before = len(nodes[node_ids[0]].committed_batches)
        t0 = time.perf_counter()
        for hb in nodes.values():
            hb.start_epoch()
        net.run()
        epoch_times.append(time.perf_counter() - t0)
        after = len(nodes[node_ids[0]].committed_batches)
        committed += sum(
            len(b)
            for b in nodes[node_ids[0]].committed_batches[before:after]
        )
    # agreement sanity: every node committed the identical history
    histories = {
        tuple(tuple(sorted(b.tx_list())) for b in hb.committed_batches)
        for hb in nodes.values()
    }
    assert len(histories) == 1, "protocol benchmark broke agreement"
    p50 = statistics.median(epoch_times) if epoch_times else None
    total_t = sum(epoch_times)
    out = {
        "epoch_p50_ms": round(p50 * 1000.0, 3) if p50 is not None else None,
        # raw per-epoch walls: relay drift (8 s -> 28 s inside one
        # session was observed in round 3) must be visible in the
        # artifact itself, not only in the evidence doc
        "epoch_times_ms": [round(t * 1000.0, 1) for t in epoch_times],
        "tx_per_sec": round(committed / total_t, 1) if total_t > 0 else None,
        "measured_epochs": len(epoch_times),
        # the hub is cluster-shared: this is ALL n validators'
        # device dispatches for the whole run, not a per-node figure
        "hub_dispatches_cluster": int(
            nodes[node_ids[0]].hub.stats()["dispatches"]
        ),
        # wave-columnar counters (ISSUE 7): how wide the hub's flush
        # columns ran and how few dispatches an epoch needed — the
        # numbers the columnar refactor is supposed to move
        "dispatches_per_epoch": round(
            nodes[node_ids[0]].hub.stats()["dispatches"]
            / max(1, epochs + 1),  # +1: warm-up epoch dispatches too
            1,
        ),
    }
    widths = sorted(nodes[node_ids[0]].hub.wave_widths)
    if widths:
        out["wave_width_p50"] = widths[len(widths) // 2]
        out["wave_width_p95"] = widths[
            max(0, int(round(0.95 * (len(widths) - 1))))
        ]
    # delivery-plane columnarization counters (ISSUE 9): payload
    # decodes and MAC-verify calls the whole run actually executed —
    # deterministic for the seeded schedule, cluster-wide (the shared
    # ChannelNetwork serves all n validators), normalized per epoch
    # (+1: the warm-up epoch's traffic counts too)
    dstats = net.delivery_stats()
    run_epochs = epochs + 1
    out["frames_decoded_per_epoch"] = round(
        dstats["frames_decoded"] / run_epochs, 1
    )
    out["mac_verifies_per_epoch"] = round(
        dstats["mac_verifies"] / run_epochs, 1
    )
    probes = dstats["decode_memo_hits"] + dstats["decode_memo_misses"]
    out["decode_memo_hit_rate"] = (
        round(dstats["decode_memo_hits"] / probes, 4) if probes else 0.0
    )
    # wave-routed ingest (ISSUE 10): batch handler invocations
    # crossing the router seam, cluster-wide (all n nodes), per epoch
    # — deterministic for the seeded schedule, the counter the router
    # exists to collapse (one per payload scalar; one per kind per
    # wave routed)
    out["handler_dispatches_per_epoch"] = round(
        sum(
            hb.metrics.handler_dispatches.value for hb in nodes.values()
        )
        / run_epochs,
        1,
    )
    # egress columnarization (ISSUE 13): outbound payload bodies
    # actually encoded, Authenticator sign passes, the encode memo's
    # hit rate, and native coin-share issue dispatches — deterministic
    # for the seeded schedule, cluster-wide, per epoch (the numbers
    # the egress/coin wave batching exists to collapse)
    out["frames_encoded_per_epoch"] = round(
        dstats["frames_encoded"] / run_epochs, 1
    )
    out["mac_signs_per_epoch"] = round(
        dstats["mac_signs"] / run_epochs, 1
    )
    eprobes = dstats["encode_memo_hits"] + dstats["encode_memo_misses"]
    out["encode_memo_hit_rate"] = (
        round(dstats["encode_memo_hits"] / eprobes, 4) if eprobes else 0.0
    )
    out["coin_dispatches_per_epoch"] = round(
        nodes[node_ids[0]].hub.stats()["coin_issue_batches"] / run_epochs,
        1,
    )
    out.update(two_frontier_keys(nodes[node_ids[0]].metrics))
    if trace:
        from cleisthenes_tpu.utils.trace import to_chrome
        from tools import tracetool

        doc = to_chrome(cluster.trace_events())
        if trace_out:
            with open(trace_out, "w") as f:
                json.dump(doc, f)
        out["stage_shares"] = tracetool.stage_shares(doc)
        out["trace_stats"] = nodes[node_ids[0]].metrics.snapshot()["trace"]
    return out


def measure_spmd(
    backend: str, n: int, batch: int, epochs: int, group=None
) -> dict:
    """Full-protocol lockstep epochs (protocol.spmd.LockstepCluster):
    every epoch performs the complete deduplicated cryptographic work
    of an N-validator HBBFT epoch — real RS/Merkle/branch-verify, real
    threshold coin per BBA round, optimistic threshold decryption —
    under the benign synchronous schedule (see the module docstring
    for exactly what is and is not exercised)."""
    from cleisthenes_tpu.protocol.spmd import LockstepCluster

    cluster = LockstepCluster(
        n=n,
        batch_size=batch,
        crypto_backend=backend,
        key_seed=77,
        group=group,
    )
    rng = np.random.default_rng(13)
    total = (batch // n) * n * (epochs + 1)
    for _ in range(total):
        tx = rng.integers(0, 256, size=TX_BYTES, dtype=np.uint8).tobytes()
        cluster.submit(tx)
    cluster.run_epoch()  # warm-up (compiles)
    times = []
    committed = 0
    rounds = []
    for _ in range(epochs):
        before = len(cluster.committed_batches)
        s = cluster.run_epoch()
        times.append(s["epoch_s"])
        rounds.append(s["bba_rounds"])
        committed += sum(
            len(b) for b in cluster.committed_batches[before:]
        )
    p50 = statistics.median(times)
    total_t = sum(times)
    return {
        "epoch_p50_ms": round(p50 * 1000.0, 3),
        "epoch_times_ms": [round(t * 1000.0, 1) for t in times],
        "tx_per_sec": round(committed / total_t, 1) if total_t else None,
        "measured_epochs": epochs,
        "bba_rounds": rounds,
    }


# ---------------------------------------------------------------------------
# Wide-group modexp: the XLA limb families past 256 bits
# ---------------------------------------------------------------------------

# RFC 3526 MODP group 14 (2048-bit safe prime)
_MODP14 = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A637ED6B0BFF5CB6F406B7ED"
    "EE386BFB5A899FA5AE9F24117C4B1FE649286651ECE45B3DC2007CB8A163BF05"
    "98DA48361C55D39A69163FA8FD24CF5F83655D23DCA3AD961C62F356208552BB"
    "9ED529077096966D670C354E4ABC9804F1746C08CA18217C32905E462E36CE3B"
    "E39E772C180E86039B2783A2EC07A28FB5C55DF06F4C52C9DE2BCBF695581718"
    "3995497CEA956AE515D2261898FA051015728E5A8AACAA68FFFFFFFFFFFFFFFF",
    16,
)


# RFC 2409 First Oakley Group (768-bit safe prime) — sized for the
# (11, 72) limb family, so all three wide families get a measured
# device-vs-host number (WIDE_FLOORS provenance)
_OAKLEY1 = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A63A3620FFFFFFFFFFFFFFFF",
    16,
)


def measure_modexp_wide() -> dict:
    """exps/s of the wide XLA limb families (384/768/2048-bit groups)
    vs the host comparator — python pow here, since the native
    Montgomery kernel is 256-bit-only (round-3 verdict item 4: these
    widths used to be REJECTED by the XLA engine)."""
    from cleisthenes_tpu.ops import modmath as mm

    rng = np.random.default_rng(29)
    out = {}
    for label, p, batch in (
        ("384", mm.P384, 2048),  # the packaged 384-bit group's prime
        ("768", _OAKLEY1, 512),  # (11,72) family
        ("2048", _MODP14, 128),
    ):
        group = mm.GroupParams(p=p, q=(p - 1) // 2, g=4)
        # uncached engine (get_engine's per-group cache would leak the
        # pin below into protocol sections), device-pinned: WIDE_FLOORS
        # would route the 2048-bit batch (measured 0.97x host) back to
        # the host and this section would measure pow against pow
        eng = mm.ModEngine("tpu", group=group)
        eng.host_delegation = False
        bases = [
            int.from_bytes(rng.bytes(group.nbytes), "big") % p
            for _ in range(batch)
        ]
        exps = [
            int.from_bytes(rng.bytes(group.nbytes), "big") % group.q
            for _ in range(batch)
        ]
        got = eng.pow_batch(bases, exps)  # warm-up (compiles)
        t0 = time.perf_counter()
        eng.pow_batch(bases, exps)
        dev_s = time.perf_counter() - t0
        sample = max(batch // 16, 8)
        t0 = time.perf_counter()
        host = [pow(b, e, p) for b, e in zip(bases[:sample], exps[:sample])]
        host_s = (time.perf_counter() - t0) * (batch / sample)
        assert got[:sample] == host, f"{label}-bit device/host mismatch"
        out[f"w{label}"] = {
            "bits": int(label),
            "batch": batch,
            "device_exps_per_sec": round(batch / dev_s, 1),
            "host_pow_exps_per_sec": round(batch / host_s, 1),
            "vs_host": _vs(host_s * 1000.0, dev_s * 1000.0),
        }
    return out


def _vs(cpu_ms, tpu_ms):
    """cpu/tpu ratio, None-safe and NaN-safe (ADVICE round-2)."""
    if (
        isinstance(cpu_ms, (int, float))
        and isinstance(tpu_ms, (int, float))
        and math.isfinite(cpu_ms)
        and math.isfinite(tpu_ms)
        and tpu_ms > 0
    ):
        return round(cpu_ms / tpu_ms, 3)
    return None


def protocol_section(backend_accel: str, backend_cpu: str, n: int,
                     batch: int, epochs: int) -> dict:
    accel = measure_protocol(backend_accel, n, batch, epochs)
    cpu = measure_protocol(backend_cpu, n, batch, epochs)
    return {
        "n": n,
        "batch": batch,
        "tpu": accel,
        "cpu": cpu,
        "vs_cpu": _vs(cpu["epoch_p50_ms"], accel["epoch_p50_ms"]),
    }


# ---------------------------------------------------------------------------
# order-then-settle overlap: the REAL pipelining number (ISSUE 8)
# ---------------------------------------------------------------------------


def measure_order_overlap(
    backend: str,
    n: int = OVERLAP_N,
    batch: int = OVERLAP_BATCH,
    epochs: int = OVERLAP_EPOCHS,
    order_then_settle: bool = True,
    pipeline_depth: int = 1,
) -> dict:
    """Chained protocol epochs through the two-frontier commit split:
    transactions pre-submitted, ``auto_propose`` on, ONE ``net.run``
    drives every epoch back to back, so epoch e+1's RBC/BBA genuinely
    overlaps epoch e's trailing decryption (Config.order_then_settle).

    ``pipeline_overlap_x`` = sum of per-epoch propose->settle walls /
    elapsed wall.  Strictly sequential epochs score <= 1.0; overlap
    pushes it above 1.0.  ``order_then_settle=False`` measures the
    coupled arm of the SAME workload — the honest comparison the
    retired crypto_n512_pipelined section never had."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    # the lead must clear depth + the default lag (read off the
    # dataclass, never a re-stated literal)
    lag = Config.__dataclass_fields__["decrypt_lag_max"].default
    cfg = Config(
        n=n,
        batch_size=batch,
        crypto_backend=backend,
        seed=99,
        order_then_settle=order_then_settle,
        # K-deep pipelined frontiers (ISSUE 15): the section sweeps
        # depth ∈ {1, 2, 4}, so K concurrent epochs share waves and
        # the per-ordered-epoch dispatch counters below move
        pipeline_depth=pipeline_depth,
        reconfig_lead=max(8, pipeline_depth + lag + 1),
    )
    cluster = SimulatedCluster(
        config=cfg, key_seed=77, auto_propose=True, shared_hub=True
    )
    rng = np.random.default_rng(13)
    node_ids = cluster.ids
    # warm-up epoch (jit compile, caches) with its own transactions —
    # add_transaction never opens an epoch, so the kick is explicit
    for i in range(batch):
        cluster.nodes[node_ids[i % n]].add_transaction(
            rng.integers(0, 256, size=TX_BYTES, dtype=np.uint8).tobytes()
        )
    for hb in cluster.nodes.values():
        hb.start_epoch()
    cluster.net.run()
    n0 = cluster.nodes[node_ids[0]]
    assert n0.settled_epoch >= 1, "warm-up epoch did not commit"
    for i in range(batch * epochs):
        cluster.nodes[node_ids[i % n]].add_transaction(
            rng.integers(0, 256, size=TX_BYTES, dtype=np.uint8).tobytes()
        )
    # time.monotonic, NOT perf_counter: the window filter below
    # compares t0 against Metrics' phase stamps, which are
    # time.monotonic values — the two clocks' epochs are not
    # comparable on every platform
    t0 = time.monotonic()
    for hb in cluster.nodes.values():  # kick; auto-propose chains on
        hb.start_epoch()
    cluster.net.run()
    elapsed = time.monotonic() - t0
    assert n0.settled_epoch == n0.epoch, "run ended with unsettled epochs"
    histories = {
        tuple(tuple(sorted(b.tx_list())) for b in hb.committed_batches)
        for hb in cluster.nodes.values()
    }
    assert len(histories) == 1, "overlap benchmark broke agreement"
    m = n0.metrics
    # per-epoch serial walls from the metrics phase traces: the warm-up
    # epoch predates t0, so only spans measured inside the window count
    measured = [
        (e, tp, tc)
        for e, tp, tc in m.epoch_spans()
        if tp >= t0 - 1e-9 and tc is not None
    ]
    spans = [(tp, tc) for _e, tp, tc in measured]
    serial = sum(tc - tp for tp, tc in spans)
    # THE two-frontier certificate: how much of the ordered->settled
    # lag (the trailing decrypt track) ran hidden under some epoch's
    # protocol window [propose, ordered].  The coupled arm has no
    # settle track at all (t_ordered unset) and scores 0 — unlike the
    # serial/elapsed ratio, which the pre-existing proposal pipelining
    # inflates on BOTH arms.
    protocol_iv = []
    settle_iv = []
    for e, tp, tc in measured:
        t_ord = m.trace(e).t_ordered
        protocol_iv.append((tp, t_ord if t_ord is not None else tc))
        if t_ord is not None:
            settle_iv.append((t_ord, tc))
    merged = []
    for p0, p1 in sorted(protocol_iv):
        if merged and p0 <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], p1))
        else:
            merged.append((p0, p1))
    settle_total = sum(s1 - s0 for s0, s1 in settle_iv)
    settle_hidden = sum(
        max(0.0, min(s1, p1) - max(s0, p0))
        for s0, s1 in settle_iv
        for p0, p1 in merged
    )
    # K-deep wave-sharing counters (ISSUE 15): cluster-wide hub/router
    # dispatch totals over the measured run, normalized per ORDERED
    # epoch — K concurrent epochs landing in the same delivery waves
    # is exactly a drop in these (the zero-noise evidence rule)
    ordered_total = max(
        1,
        n0.metrics.epochs_ordered.value or n0.settled_epoch,
    )
    hub_stats = n0.hub.stats()
    handler_total = sum(
        hb.metrics.handler_dispatches.value
        for hb in cluster.nodes.values()
    )
    widths = sorted(n0.hub.wave_widths)
    out = {
        "n": n,
        "batch": batch,
        "mode": (
            "order_then_settle" if order_then_settle else "coupled"
        ),
        "pipeline_depth": pipeline_depth,
        "measured_epochs": len(spans),
        "elapsed_wall_ms": round(elapsed * 1000.0, 3),
        "serial_epoch_walls_ms": round(serial * 1000.0, 3),
        # > 1.0 means epochs genuinely overlapped (an epoch's settle
        # ran under a later epoch's RBC/BBA); sequential epochs bound
        # this at <= ~1.0 by construction
        "pipeline_overlap_x": (
            round(serial / elapsed, 3) if elapsed > 0 else None
        ),
        # fraction of the settle track hidden under protocol windows
        # (0.0 on the coupled arm — it has no settle track)
        "settle_hidden_frac": (
            round(settle_hidden / settle_total, 3)
            if settle_total > 0
            else 0.0
        ),
        "settle_track_ms": round(settle_total * 1000.0, 3),
        "epoch_p50_ms": (
            round(statistics.median([tc - tp for tp, tc in spans])
                  * 1000.0, 3)
            if spans
            else None
        ),
        # per-ordered-epoch dispatch amortization (counter-based,
        # deterministic for the seeded schedule)
        "hub_dispatches_per_ordered_epoch": round(
            hub_stats["dispatches"] / ordered_total, 1
        ),
        "hub_flushes_per_ordered_epoch": round(
            hub_stats["flushes"] / ordered_total, 1
        ),
        "handler_dispatches_per_ordered_epoch": round(
            handler_total / ordered_total, 1
        ),
        "eager_share_waves": int(
            sum(
                hb.metrics.eager_share_waves.value
                for hb in cluster.nodes.values()
            )
        ),
        "wave_width_p50": (
            widths[len(widths) // 2] if widths else None
        ),
        # same index rule as the protocol sections above, so the key
        # means the same thing in every section of one report
        "wave_width_p95": (
            widths[max(0, int(round(0.95 * (len(widths) - 1))))]
            if widths
            else None
        ),
    }
    out.update(two_frontier_keys(m))
    return out


def order_overlap_section(backend: str) -> dict:
    """The same seeded workload across the commit/pipelining arms:
    the two-frontier split at K-deep window depths 1, 2 and 4
    (ISSUE 15 — depth 1 is the lockstep comparison arm) vs the
    coupled commit path — all paired on one box, back to back."""
    depths = {
        depth: measure_order_overlap(
            backend, order_then_settle=True, pipeline_depth=depth
        )
        for depth in (1, 2, 4)
    }
    split = depths[1]
    coupled = measure_order_overlap(backend, order_then_settle=False)
    return {
        "n": OVERLAP_N,
        "batch": OVERLAP_BATCH,
        "epochs": OVERLAP_EPOCHS,
        "order_then_settle": split,
        "depth2": depths[2],
        "depth4": depths[4],
        "coupled": coupled,
        # the headline: settled-throughput ratio of split vs coupled
        # on identical submitted work (elapsed wall, lower is better)
        "split_vs_coupled_wall_x": _vs(
            coupled["elapsed_wall_ms"], split["elapsed_wall_ms"]
        ),
        # K-deep headlines: overlap and wall ratio per depth vs the
        # depth-1 arm of the identical workload, plus the wave-width
        # delta (K epochs sharing waves widens each hub flush)
        "pipeline_overlap_x_by_depth": {
            str(d): depths[d]["pipeline_overlap_x"] for d in depths
        },
        "depth4_vs_depth1_wall_x": _vs(
            split["elapsed_wall_ms"], depths[4]["elapsed_wall_ms"]
        ),
        "wave_width_p50_by_depth": {
            str(d): depths[d]["wave_width_p50"] for d in depths
        },
        "hub_dispatches_per_ordered_epoch_by_depth": {
            str(d): depths[d]["hub_dispatches_per_ordered_epoch"]
            for d in depths
        },
    }


# ---------------------------------------------------------------------------
# WAN emulation scenarios (ISSUE 16): geo-realistic schedules
# ---------------------------------------------------------------------------


def measure_wan(backend: str, profile: str, n: int = 4,
                batch: int = 32, epochs: int = 3) -> dict:
    """One seeded WAN profile end to end: n validators over the
    channel transport with the link-model plane mounted, ``epochs``
    committed epochs back to back.  The headline is virtual time per
    settled epoch (the geo-latency cost the link model charges the
    schedule) next to host wall — plus the model's own evidence
    (retransmits, straggler episodes, frames delayed)."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    cfg = Config(n=n, batch_size=batch, crypto_backend=backend, seed=5)
    cluster = SimulatedCluster(
        config=cfg,
        key_seed=55,
        auto_propose=True,
        shared_hub=True,
        wan_profile=profile,
    )
    rng = np.random.default_rng(21)
    t0 = time.perf_counter()
    for _ in range(epochs):
        for _ in range(batch):
            cluster.submit(
                rng.integers(
                    0, 256, size=TX_BYTES, dtype=np.uint8
                ).tobytes()
            )
        cluster.run_until_drained(max_rounds=80)
    wall_ms = (time.perf_counter() - t0) * 1000.0
    cluster.assert_agreement()
    n0 = cluster.nodes[cluster.ids[0]]
    settled = n0.settled_epoch + 1
    assert settled >= epochs, (
        f"wan profile {profile}: settled {settled} < {epochs}"
    )
    stats = cluster.net.wan.stats()
    health = cluster.health()
    return {
        "profile": profile,
        "settled_epochs": settled,
        "virtual_ms_per_epoch": round(
            int(stats["virtual_time_ms"]) / settled, 1
        ),
        "wall_ms_per_epoch": round(wall_ms / settled, 1),
        "frames_delayed": stats["frames_delayed"],
        "retransmits": stats["retransmits"],
        "straggler_episodes": stats["straggler_episodes"],
        "health": health["status"],
    }


def wan_section(backend: str) -> dict:
    """The named profile matrix under the SAME seeded workload: how
    much schedule time each geography charges, and that every profile
    still commits with agreement (the degradation-hardening evidence
    next to the perf numbers)."""
    from cleisthenes_tpu.transport.wan import wan_profile_names

    return {
        profile: measure_wan(backend, profile)
        for profile in wan_profile_names()
    }


def ingress_section() -> dict:
    """Client-visible latency under open-loop ingress load (ISSUE 18):
    a seeded Pareto-bursty client population driven through the
    production admission path (tools/loadgen.py — in-proc twin of the
    client gRPC surface, fee-priority mempool, channel transport),
    one arm per pipeline depth in {1, 4} over the IDENTICAL arrival
    schedule.  Headlines are submit->ordered and submit->settled
    p50/p99 plus sustained settled tx/s; the harness asserts zero
    lost acks and byte-identical settled content across arms before
    reporting anything.  A wan-composed arm (the PR-16 link model
    under the same load) rides along at depth 4.  CPU-plane only —
    the admission path runs in the scheduler, not on the chip."""
    from tools import loadgen

    schedule = loadgen.build_schedule(
        clients=20_000, txs=6_000, ticks=24, seed=7
    )
    arms = {}
    for depth in (1, 4):
        a = loadgen.run_arm(
            schedule, depth=depth, n=4, batch=256, seed=7
        )
        arms[f"depth{depth}"] = {
            k: a[k]
            for k in (
                "submit_to_ordered_ms", "submit_to_settled_ms",
                "tx_per_s", "settled", "evicted", "epochs",
                "ledger_digest",
            )
        }
    digests = {a["ledger_digest"] for a in arms.values()}
    assert len(digests) == 1, f"ingress arms diverged: {arms}"
    wan = loadgen.run_arm(
        schedule, depth=4, n=4, batch=256, seed=7,
        wan_profile="wan_3region",
    )
    arms["depth4_wan_3region"] = {
        k: wan[k]
        for k in (
            "submit_to_ordered_ms", "submit_to_settled_ms",
            "tx_per_s", "settled", "ledger_digest",
        )
    }
    return {
        "clients": 20_000,
        "txs": 6_000,
        "mode": "open-loop Pareto arrivals via the in-proc ingress "
        "twin (tools/loadgen.py); arms share one seeded schedule",
        "arms": arms,
    }


# ---------------------------------------------------------------------------
# lane shard-out scaling (ISSUE 20): S parallel consensus lanes
# ---------------------------------------------------------------------------


def _lane_balanced_txs(S: int, per_lane: int, seed: int) -> dict:
    """Per-lane tx quotas under the PRODUCTION partitioner: random
    64-byte payloads classified by ``lane_of(seed, digest, S)`` until
    every lane holds exactly ``per_lane``.  A scaling benchmark wants
    fixed-shape load per arm (like a fixed batch shape); the natural
    hash skew across (node, lane) admission cells is measured
    separately by the loadgen lane-skew headline."""
    from cleisthenes_tpu.core.merge import lane_of
    from cleisthenes_tpu.core.mempool import tx_digest

    rng = np.random.default_rng(seed)
    quota: dict = {k: [] for k in range(S)}
    while any(len(v) < per_lane for v in quota.values()):
        tx = rng.integers(0, 256, size=TX_BYTES, dtype=np.uint8).tobytes()
        k = lane_of(seed, tx_digest(tx), S)
        if len(quota[k]) < per_lane:
            quota[k].append(tx)
    return quota


def measure_lane_scaling(S: int, n: int = 16, batch: int = 64,
                         epochs_per_lane: int = 4, seed: int = 41,
                         profile: str = "wan_3region") -> dict:
    """One lane-count arm: n validators, S sibling HBBFT lanes over
    the ONE roster/transport/hub, lane-balanced load, run to drain
    under a seeded WAN profile.  Headlines are tx per VIRTUAL second
    (the link-model clock: S lanes' epochs ride the same geo round
    trips, so settled slots per virtual second scale with S) next to
    honest wall tx/s (the serialized one-process scheduler pays S
    lanes' crypto mass sequentially, so wall throughput must NOT be
    read as the scaling evidence) and hub dispatches per ordered
    lane-epoch (the flatness criterion: the wave coalescer carries
    all S lanes' traffic per flush, so dispatch counts must not grow
    ~linearly in S)."""
    from cleisthenes_tpu.config import Config
    from cleisthenes_tpu.protocol.cluster import SimulatedCluster

    cfg = Config(
        n=n, batch_size=batch, crypto_backend="cpu", seed=seed, lanes=S
    )
    cluster = SimulatedCluster(
        config=cfg, seed=seed, shared_hub=True, wan_profile=profile
    )
    quota = _lane_balanced_txs(S, batch * epochs_per_lane, seed)
    ids = cluster.ids
    for txs in quota.values():
        for i, tx in enumerate(txs):
            cluster.nodes[ids[i % n]].add_transaction(tx)
    t0 = time.perf_counter()
    cluster.run_until_drained(max_rounds=600)
    wall_s = time.perf_counter() - t0
    cluster.assert_agreement()
    n0 = cluster.nodes[cluster.ids[0]]
    settled_tx = sum(
        sum(len(v) for v in b.contributions.values())
        for b in n0.merged_batches
    )
    assert settled_tx == S * batch * epochs_per_lane, (
        f"lanes={S}: settled {settled_tx} of "
        f"{S * batch * epochs_per_lane} submitted txs"
    )
    virtual_ms = int(cluster.net.wan.stats()["virtual_time_ms"])
    slots = n0.merged_settled_frontier
    ordered = sum(hb.epoch for hb in n0.lanes)
    hub = n0.hub.stats()["dispatches"]
    return {
        "lanes": S,
        "n": n,
        "batch": batch,
        "settled_tx": settled_tx,
        "merged_slots": slots,
        "virtual_ms": virtual_ms,
        "virtual_ms_per_slot": round(virtual_ms / slots, 1),
        "tx_per_virtual_sec": round(settled_tx / (virtual_ms / 1e3), 1),
        "wall_tx_per_sec": round(settled_tx / wall_s, 1),
        "hub_dispatches_per_ordered_epoch": round(hub / ordered, 2),
    }


def lane_scaling_section() -> dict:
    """Horizontal shard-out (ISSUE 20): S ∈ {1, 2, 4} sibling lanes at
    n=16 under one seeded WAN geography, plus one S=4 arm at n=64.

    The scaling headline is latency-bound throughput — tx per virtual
    second on the link-model clock — because in the serialized
    one-process simulation every lane's crypto runs on the same host
    core: wall tx/s CANNOT scale with S here and is reported next to
    the virtual-time number precisely so nobody mistakes either for
    the other.  The flatness headline (hub dispatches per ordered
    lane-epoch) shows the wave coalescer amortizing all S lanes into
    shared flushes — it FALLS with S rather than staying merely
    flat, because one physical wave now carries S lanes' frames."""
    arms = {f"S{S}": measure_lane_scaling(S) for S in (1, 2, 4)}
    # the width arm: the same 4-lane shard-out over a 64-validator
    # roster (f=21), one epoch per lane — evidence the lane axis
    # composes with roster width, not a cadence measurement
    arms["S4_n64"] = measure_lane_scaling(
        4, n=64, epochs_per_lane=1
    )
    s1, s4 = arms["S1"], arms["S4"]
    return {
        "mode": (
            "lane-balanced 64B txs via the production hash "
            "partitioner; run_until_drained under wan_3region; "
            "virtual-time cadence is the scaling evidence, wall tx/s "
            "the honest serialized-simulation cost"
        ),
        "arms": arms,
        "s4_vs_s1_tx_per_virtual_sec_x": _vs(
            1.0 / s1["tx_per_virtual_sec"], 1.0 / s4["tx_per_virtual_sec"]
        ),
        "s4_vs_s1_wall_tx_per_sec_x": _vs(
            1.0 / s1["wall_tx_per_sec"], 1.0 / s4["wall_tx_per_sec"]
        ),
        "hub_dispatches_per_ordered_epoch_by_S": {
            str(a["lanes"]): a["hub_dispatches_per_ordered_epoch"]
            for a in (arms["S1"], arms["S2"], arms["S4"])
        },
    }


# ---------------------------------------------------------------------------
# harness: subprocess isolation + relay probing + guaranteed JSON output
# ---------------------------------------------------------------------------


def run_child() -> None:
    """The actual measurement; prints the JSON result line.

    Runs in a subprocess so a hung TPU relay (which cannot be
    interrupted in-process) is bounded by the parent's timeout.
    """
    import jax

    dev = jax.devices()[0]
    # the axon relay's PJRT plugin presents real chips as platform
    # 'tpu' (device_kind e.g. 'TPU v5 lite'); the forced fallback is
    # platform 'cpu'
    platform = dev.platform
    device_kind = getattr(dev, "device_kind", "")
    on_tpu = platform in ("tpu", "axon")

    def progress(section: str) -> None:
        print(f"[bench] {section} @ {time.strftime('%H:%M:%S')}",
              file=sys.stderr, flush=True)

    def dispatch_ms() -> float:
        """One tiny forced dispatch: the relay-health needle.  A
        healthy relay round-trips ~40 ms; recording it at start AND
        end makes intra-session relay drift (8 s -> 28 s epochs in
        round 3) visible inside the artifact."""
        import jax.numpy as jnp

        t0 = time.perf_counter()
        np.asarray(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
        return round((time.perf_counter() - t0) * 1000.0, 1)

    provenance = {
        "start_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "dispatch_ms_start": dispatch_ms(),
        # host-contention evidence (VERDICT r4 weak #2: a concurrent
        # watcher probe silently inflated every CPU section ~2x)
        "host_load_start": _load_snapshot(),
    }
    # Per-section persistence: a child killed by the parent's timeout
    # (or a dying relay window) keeps every section it finished — the
    # parent salvages this file instead of discarding a 50-min run
    # (which is exactly what happened to the first round-5 capture).
    out: dict = {"partial": True, "provenance": provenance}

    def persist() -> None:
        try:
            tmp = _PARTIAL_PATH + ".tmp"
            with open(tmp, "w") as f:
                json.dump(out, f)
            os.replace(tmp, _PARTIAL_PATH)
        except OSError:
            pass

    _progress_plain = progress

    def progress(section: str) -> None:  # noqa: F811 — wrap: persist too
        persist()
        _progress_plain(section)

    cpu_ref = cpu_reference_backend()
    progress(f"platform={platform} ({device_kind}); crypto_n128 tpu")
    accel_p50 = measure_crypto("tpu")
    progress("crypto_n128 cpu")
    cpu_p50 = measure_crypto(cpu_ref)
    out.update({
        "metric": "epoch_crypto_p50_n128_f42_b10k",
        "value": round(accel_p50 * 1000.0, 3),
        "unit": "ms",
        "vs_baseline": _vs(cpu_p50 * 1000.0, accel_p50 * 1000.0),
        "platform": platform,
        "device": device_kind,
        "cpu_reference": cpu_ref,
        "baseline_note": (
            "CPU GF plane uses native C++ kernels when available; "
            + modexp_comparator_note()
        ),
    })
    # Section order is salvage-priority order: a dying window (or the
    # parent's child timeout) keeps the sections already persisted, so
    # the headline lockstep/wide sections run BEFORE the slow live-
    # protocol ones (round 5 lost a 50-min capture tail-first).
    # full-protocol lockstep epochs at the BASELINE config-4 scale
    # (N=128, f=42, 10k-tx batches) — the SPMD executor
    progress("protocol_spmd_n128 cpu")
    spmd_cpu = measure_spmd(cpu_ref, 128, 10_000, 3 if on_tpu else 2)
    spmd_tpu = None
    if on_tpu:
        progress("protocol_spmd_n128 tpu")
        spmd_tpu = measure_spmd("tpu", 128, 10_000, 3)
    out["protocol_spmd_n128"] = {
        "n": 128, "f": 42, "batch": 10_000,
        "mode": "lockstep (protocol.spmd; benign synchronous schedule, "
                "full dedup'd crypto, wire/MAC layer not exercised)",
        "tpu": spmd_tpu,
        "cpu": spmd_cpu,
        "vs_cpu": (
            _vs(spmd_cpu["epoch_p50_ms"], spmd_tpu["epoch_p50_ms"])
            if spmd_tpu
            else None
        ),
    }
    if on_tpu:
        # The flagship roster under a production-width group (round-4
        # verdict item 5): the SAME full lockstep protocol — TPKE,
        # coin, RS, Merkle — with every exponentiation in the 384-bit
        # safe-prime group (BLS12-381 base-field width class, (12,32)
        # XLA limb family) instead of the 256-bit research group.  The
        # CPU comparator is python pow at this width (native kernel is
        # 256-only), measured at 1 epoch to bound its cost.
        from cleisthenes_tpu.ops.modmath import GROUP384

        progress("protocol_spmd_n128_g384 tpu")
        g384_tpu = measure_spmd("tpu", 128, 10_000, 2, group=GROUP384)
        progress("protocol_spmd_n128_g384 cpu")
        g384_cpu = measure_spmd(
            cpu_ref, 128, 10_000, 1, group=GROUP384
        )
        out["protocol_spmd_n128_g384"] = {
            "n": 128, "f": 42, "batch": 10_000,
            "group_bits": 384,
            "mode": "lockstep, GROUP384 end-to-end (TPKE + coin); "
                    "cpu modexp comparator is python pow",
            "tpu": g384_tpu,
            "cpu": g384_cpu,
            "vs_cpu": _vs(
                g384_cpu["epoch_p50_ms"], g384_tpu["epoch_p50_ms"]
            ),
            # the price of width on the SAME backend (vs the 256-bit
            # flagship section above)
            "g384_over_g256_tpu": _vs(
                g384_tpu["epoch_p50_ms"],
                spmd_tpu["epoch_p50_ms"] if spmd_tpu else None,
            ),
        }
    if on_tpu:
        # BASELINE config 5 as a TRUE full-protocol run: N=512
        # validators through RBC + BBA + TPKE in lockstep, on the
        # GF(2^16) codec (the reference's codec dependency caps at 256
        # shards, so its lineage cannot express this roster at all).
        # The exponentiation mass at this roster (~1.9M per epoch)
        # dwarfs dispatch overhead — the scale where the chip should
        # win decisively, so the cpu comparator IS measured despite
        # its cost (~90 s/epoch native, round-4 measurement; one
        # measured epoch + warm-up ≈ 3 min of the budget).
        progress("protocol_spmd_n512 tpu")
        n512_tpu = measure_spmd("tpu", 512, 4096, 2)
        progress("protocol_spmd_n512 cpu")
        n512_cpu = measure_spmd(cpu_ref, 512, 4096, 1)
        out["protocol_spmd_n512"] = {
            "n": 512, "f": 170, "batch": 4096,
            "mode": "lockstep, GF(2^16) erasure codec",
            "tpu": n512_tpu,
            "cpu": n512_cpu,
            "vs_cpu": _vs(
                n512_cpu["epoch_p50_ms"], n512_tpu["epoch_p50_ms"]
            ),
        }
    # order-then-settle overlap (ISSUE 8): replaces the retired
    # crypto_n512_pipelined section — a software pipeline over one
    # dispatch queue whose overlap_x ~0.95 said nothing.  Runs on the
    # REAL protocol path; the CPU arm is the headline (the split is a
    # protocol-structure win, not a chip win), with an accelerated arm
    # recorded when a TPU is attached.
    progress("order_overlap cpu")
    out["order_overlap"] = {"cpu": order_overlap_section(cpu_ref)}
    if on_tpu:
        progress("order_overlap tpu")
        out["order_overlap"]["tpu"] = order_overlap_section("tpu")
    # WAN emulation scenarios (ISSUE 16): virtual geo-latency charged
    # per settled epoch across the named profile matrix.  A protocol-
    # structure artifact like order_overlap — cpu arm only (the link
    # model runs in the scheduler, not on the chip).
    progress("wan_scenarios")
    out["wan_scenarios"] = wan_section(cpu_ref)
    # ingress load (ISSUE 18): client-visible submit->ordered /
    # submit->settled latency through the production admission path,
    # depth arms over one seeded schedule + a wan-composed arm.
    # Scheduler-plane like wan_scenarios — cpu only.
    progress("ingress_load")
    out["ingress_load"] = ingress_section()
    # lane shard-out (ISSUE 20): S sibling consensus lanes over one
    # roster, virtual-time cadence + dispatch flatness vs S.
    # Scheduler-plane like wan_scenarios — cpu only.
    progress("lane_scaling")
    out["lane_scaling"] = lane_scaling_section()
    progress("modexp_wide")
    if on_tpu:
        # first time these wide-limb programs meet a real chip: a
        # pathological compile or relay death here must cost this
        # SECTION, not the whole artifact
        try:
            out["modexp_wide"] = measure_modexp_wide()
        except Exception as exc:  # noqa: BLE001 — recorded, not hidden
            out["modexp_wide"] = {"error": repr(exc)[:300]}
    else:
        out["modexp_wide"] = {
            "note": "skipped: no TPU attached (XLA-on-host wide-limb "
            "numbers are meaningless and ~85 s of budget)"
        }
    # live-protocol sections (the slowest) run LAST: see the salvage-
    # priority note above
    for name, pc in PROTO_CONFIGS.items():
        progress(name)
        if on_tpu:
            # Both backends run every live-protocol section on a real
            # chip: the host floors (ModEngine.HOST_FLOOR,
            # XlaMerkle.HOST_FLOOR_*) route sub-crossover batches to
            # the native kernels, so the 'tpu' backend no longer
            # drowns small-N waves in per-dispatch RTT (the round-2
            # failure mode that made n64-accelerated opt-in).
            out[name] = protocol_section(
                "tpu", cpu_ref, pc["n"], pc["batch"], pc["epochs"]
            )
        else:
            # Relay-down fallback: XLA-on-host 'tpu' numbers are a
            # meaningless stand-in AND slow — the full fallback run
            # measured 74 min, a budget risk for the driver.  Record
            # the native-path numbers only.
            out[name] = {
                "n": pc["n"], "batch": pc["batch"],
                "cpu": measure_protocol(
                    cpu_ref, pc["n"], pc["batch"], pc["epochs"]
                ),
                "tpu": None, "vs_cpu": None,
                "note": "accelerated side skipped: no TPU attached",
            }
    progress("done")  # persists the live sections before finalizing
    provenance["end_utc"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
    )
    provenance["dispatch_ms_end"] = dispatch_ms()
    provenance["host_load_end"] = _load_snapshot()
    out["provenance"] = provenance
    del out["partial"]  # completed run: not a salvage artifact
    persist()
    print(json.dumps(out))


CHILD_TIMEOUT_S = int(os.environ.get("BENCH_CHILD_TIMEOUT_S", "3000"))
# where the child persists completed sections (parent salvages on
# timeout; a finished run overwrites it with the final artifact)
_PARTIAL_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_PARTIAL.json"
)


def _spawn_child(force_cpu: bool) -> "tuple[dict | None, str]":
    """Run the measurement subprocess; return (parsed JSON, detail)."""
    env = dict(os.environ)
    if force_cpu:
        # skip the axon PJRT plugin registration entirely so the dead
        # relay is never touched; the XLA path then runs on host CPU
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
    t_start = time.time()
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            timeout=CHILD_TIMEOUT_S,
            capture_output=True,
            text=True,
            env=env,
        )
    except subprocess.TimeoutExpired:
        doc = _salvage_partial(
            t_start,
            f"child timed out after {CHILD_TIMEOUT_S}s; completed "
            "sections salvaged from the child's per-section persistence",
        )
        if doc is not None:
            return doc, ""
        return None, f"timeout after {CHILD_TIMEOUT_S}s"
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                return parsed, ""
        except json.JSONDecodeError:
            continue
    tail = (r.stderr or r.stdout or "").strip().splitlines()
    detail = f"rc={r.returncode}: {' | '.join(tail[-3:]) or 'no output'}"
    # a child that CRASHED mid-run (relay death aborting the process,
    # not just outliving the cap) also keeps its persisted sections
    doc = _salvage_partial(
        t_start,
        f"child died before finishing ({detail}); completed sections "
        "salvaged from the child's per-section persistence",
    )
    if doc is not None:
        return doc, ""
    return None, detail


def _salvage_partial(t_start: float, note: str) -> "dict | None":
    """The child persists every completed section to _PARTIAL_PATH; a
    run that dies (timeout OR crash) must not collapse a 50-min TPU
    capture into a CPU fallback (round-5 capture #1 was lost exactly
    this way)."""
    try:
        if os.path.getmtime(_PARTIAL_PATH) < t_start:
            return None  # stale: from some earlier run
        with open(_PARTIAL_PATH) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(doc, dict) and doc.get("metric"):
        doc["note"] = note
        return doc
    return None


def _probe_relay(timeout_s: int = 90) -> bool:
    """Cheap subprocess probe: can the default backend run one op?

    A dead axon relay hangs indefinitely on first dispatch, so the
    probe (not the full measurement) is what bounds the cost of
    discovering an outage.
    """
    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "print('PROBE_OK' if float(np.asarray(jnp.ones(8).sum())) == 8.0"
        " else 'PROBE_BAD')"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PROBE_OK" in r.stdout


def main() -> None:
    """Driver entry: bounded retry on the TPU relay, CPU-XLA fallback,
    and ALWAYS one parseable JSON line on stdout (never a bare
    traceback — the round-1 failure mode, BENCH_r01.json rc=1).
    A healthy relay automatically yields platform='axon' provenance in
    the recorded artifact (VERDICT round-2 item 5)."""
    # exclusive measurement lock: no watcher probe, quick capture, or
    # background sweep may share the one core while we measure
    # (round-4 driver capture was contaminated exactly that way)
    try:
        with benchlock.hold("bench.py"):
            _run_locked()
    except TimeoutError as exc:
        # the one-JSON-line contract holds even when the lock is wedged
        print(
            json.dumps(
                {
                    "metric": "epoch_crypto_p50_n128_f42_b10k",
                    "value": None,
                    "unit": "ms",
                    "vs_baseline": None,
                    "platform": None,
                    "error": f"bench lock unavailable: {exc}",
                }
            )
        )


def _run_locked() -> None:
    errors = []
    healthy = False
    for attempt in range(2):
        if _probe_relay():
            healthy = True
            break
        errors.append(f"probe {attempt + 1}: relay unreachable")
        time.sleep(5)
    if healthy:
        result, detail = _spawn_child(force_cpu=False)
        if result is not None:
            _append_trend(result)
            print(json.dumps(result))
            return
        errors.append(f"tpu run: {detail}")
    result, detail = _spawn_child(force_cpu=True)
    if result is not None:
        result["note"] = (
            "axon TPU relay unavailable; XLA path measured on host CPU "
            f"({'; '.join(errors)})"
        )
        _append_trend(result)
        print(json.dumps(result))
        return
    errors.append(f"cpu fallback: {detail}")
    print(
        json.dumps(
            {
                "metric": "epoch_crypto_p50_n128_f42_b10k",
                "value": None,
                "unit": "ms",
                "vs_baseline": None,
                "platform": None,
                "error": "; ".join(errors),
            }
        )
    )


def run_trace() -> None:
    """bench.py --trace [--trace-out PATH]: the protocol_n16 scenario
    under the flight recorder (utils/trace.py) — one JSON line whose
    ``stage_shares`` sits next to ``epoch_p50_ms`` and says where the
    epoch's wall time went (rbc/bba/coin/tpke/hub/transport/...), so
    BENCH_* numbers finally explain themselves.  Runs on the CPU
    reference backend: the breakdown is about epoch anatomy, not the
    chip.  ``--trace-out`` additionally writes the Perfetto-loadable
    artifact (docs/TRACING.md)."""
    import argparse

    ap = argparse.ArgumentParser(prog="bench.py --trace")
    ap.add_argument("--trace", action="store_true")
    ap.add_argument("--trace-out", metavar="PATH", default=None)
    args, _unknown = ap.parse_known_args()
    pc = PROTO_CONFIGS["protocol_n16"]
    try:
        # same exclusive-measurement contract as main(): a traced
        # epoch number sharing the core with another capture is
        # contaminated in BOTH directions
        with benchlock.hold("bench.py --trace"):
            result = measure_protocol(
                cpu_reference_backend(),
                pc["n"],
                pc["batch"],
                pc["epochs"],
                trace=True,
                trace_out=args.trace_out,
            )
    except TimeoutError as exc:
        print(
            json.dumps(
                {
                    "metric": "trace_protocol_n16",
                    "error": f"bench lock unavailable: {exc}",
                }
            )
        )
        return
    doc = {
        "metric": "trace_protocol_n16",
        "n": pc["n"],
        "batch": pc["batch"],
        **result,
    }
    # the traced run carries the richest trend record of all: p50 AND
    # stage shares AND the deterministic dispatch count
    _append_trend({"platform": "cpu", "trace_protocol_n16": {
        "n": pc["n"], "batch": pc["batch"], "cpu": result,
    }})
    print(json.dumps(doc))


def run_ab() -> None:
    """bench.py --ab BASE_REF [...]: same-box interleaved A/B vs a git
    ref with paired deltas (tools/abench.py) — the comparison form
    that survives the cross-box irreproducibility WAVE_EVIDENCE.md
    documents.  Holds the measurement lock like every other mode."""
    argv = list(sys.argv[1:])
    argv.remove("--ab")
    from tools import abench

    try:
        with benchlock.hold("bench.py --ab"):
            sys.exit(abench.main(argv))
    except TimeoutError as exc:
        print(
            json.dumps(
                {
                    "metric": "abench_paired",
                    "error": f"bench lock unavailable: {exc}",
                }
            )
        )
        sys.exit(1)


if __name__ == "__main__":
    if "--child" in sys.argv:
        run_child()
    elif "--ab" in sys.argv:
        run_ab()
    elif "--trace" in sys.argv:
        run_trace()
    else:
        main()
