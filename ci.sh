#!/usr/bin/env bash
# CI gate: the one command that gates the tree.
#
# Mirrors the reference's PR pipeline (reference .travis.yml:24-27 +
# travis/run_on_pull_requests.sh: goimports format gate, `go test -v`,
# then `go test -race`), translated to this stack:
#
#   1. format/syntax gate  — compileall + tools/format_gate.py (the
#      image bakes no third-party formatter; the gate enforces this
#      tree's deterministic style invariants — parseability, LF, EOF
#      newline, no tabs/trailing whitespace, <= 99 cols — stdlib-only)
#   2. staticcheck gate    — tools/staticcheck: the three-pass
#      whole-program analyzer over the package + tools + tests
#      (per-file rules DET001-DET006/CONC001/CONC002/ERR001, the
#      cross-module registry rules WIRE001 wire-kind/pb-tag coverage,
#      SCHEMA001 counter/snapshot/golden-exposition parity, ARM001
#      arm-flag/wave-seam/fingerprint parity, VERIFY001
#      verify-before-dispatch taint walk, plus the pass-3 call-graph
#      rules CONC003 caller-holds-lock discipline, CONC004 blocking
#      reachability from dispatcher callbacks, DET007 interprocedural
#      entropy taint), with --audit-pragmas
#      failing on stale pragmas and pragma-count growth past the
#      budget in baseline.json.  Fails on ANY unbaselined finding;
#      the committed baseline is empty — every sanctioned exception
#      is a justified pragma.  A few seconds and stdlib-only, so
#      CI_FAST runs it too.  Rule catalog: docs/STATICCHECK.md.
#   3. observability gate  — a seeded 4-node traced cluster captures
#      a flight-recorder artifact (utils/trace.py) and
#      tools/tracetool.py --validate gates its schema + per-node
#      monotone sequence numbers, so the tracing plane cannot rot
#      silently between perf rounds (docs/TRACING.md)
#   4. perf-regression gate — tools/perfgate.py runs a seeded traced
#      mini-bench (4 nodes, 3 epochs) and compares epoch p50, the
#      DETERMINISTIC hub-dispatch count, and per-stage wall shares
#      against the trailing BENCH_TREND.jsonl records with noise
#      bands; the first run seeds the trend file (always passes)
#   5. ingress smoke load  — tools/loadgen.py --smoke: a seeded
#      open-loop client band through the production admission path
#      (ingress twin + fee-priority mempool); zero lost acks,
#      settled ⊇ ordered at drain, and byte-identical settled
#      content across pipeline depths gate the merge (ISSUE 18)
#   6. fast test tier      — pytest minus the multi-minute scale
#      tests, under tools/covgate.py (PEP 669 line coverage; the
#      tier must execute >= 85% of the package's executable lines —
#      the travis pipeline's coverage upload, translated to a GATE)
#   7. race-analog tier    — the seeded deterministic-scheduler suites
#      (transport/byzantine), this stack's answer to `-race`
#      (SURVEY.md §5.2: replayable interleavings instead of a dynamic
#      race detector), plus the real-thread gRPC suite
#   8. lock sanitizer      — the lock-sensitive tier-1 subset +
#      a 20-seed fuzz band re-run under CLEISTHENES_LOCKCHECK=1: the
#      runtime @guarded_by sanitizer (utils/lockcheck.py, the dynamic
#      twin of CONC001/CONC003) asserts every guarded attribute
#      access holds its declared lock; zero violations gate
#   9. fault tier          — the crash/partition/adversary suite
#      (`-m faults`: Byzantine coalitions, crash+WAL-restart+CATCHUP,
#      gRPC backoff redial) replayed over a fixed 3-seed matrix, so a
#      fault-handling regression on ANY matrix seed gates the merge
#  10. fuzz smoke          — tools/fuzz.py over a fixed seed band:
#      composite semantic (protocol/byzantine) + wire (Coalition) +
#      crash/partition schedules on seeded 4-node clusters, safety
#      invariants checked at every quiescence point; a violation
#      shrinks to a minimal replayable repro.  The deep band (200
#      seeds) rides the slow tier (tests/test_fuzz.py)
#  11. full tier           — everything, including the N=64 slow test
#      (skipped when CI_FAST=1)
#
# Usage:  ./ci.sh          # full gate
#         CI_FAST=1 ./ci.sh  # pre-push quick gate

set -euo pipefail
cd "$(dirname "$0")"

echo "== [1/11] syntax + format gate"
python -m compileall -q cleisthenes_tpu tests bench.py __graft_entry__.py
python tools/format_gate.py

echo "== [2/11] staticcheck gate: whole-program registry + determinism plane"
python -m tools.staticcheck cleisthenes_tpu tools tests --audit-pragmas

echo "== [3/11] observability gate: traced seeded cluster -> tracetool --validate"
TRACE_ARTIFACT="$(mktemp /tmp/cleisthenes_trace_ci.XXXXXX.json)"
trap 'rm -f "$TRACE_ARTIFACT"' EXIT
JAX_PLATFORMS=cpu python -m tools.tracetool \
    --capture "$TRACE_ARTIFACT" --n 4 --seed 7 --txs 24
python -m tools.tracetool "$TRACE_ARTIFACT" --validate

echo "== [4/11] perf-regression gate: seeded mini-bench vs BENCH_TREND.jsonl"
# seeded traced mini-bench through tools/perfgate.py; seeds the trend
# on the first run, gates epoch-p50 / dispatch-count / stage-share
# regressions (noise-banded) on every later run and appends on pass
JAX_PLATFORMS=cpu python -m tools.perfgate --trend BENCH_TREND.jsonl

echo "== [5/11] ingress smoke load: seeded open-loop client band"
# tools/loadgen.py --smoke (ISSUE 18): a seconds-scale seeded Pareto
# client population driven through the production admission path (the
# in-proc twin of the client gRPC surface + fee-priority mempool).
# The harness asserts zero lost acks (every OK-acked tx settles
# exactly once or is accounted by the eviction counter), the settled
# frontier catching the ordered frontier at drain, cross-node
# agreement, and byte-identical settled content across pipeline
# depths 1 and 4 before reporting any latency
JAX_PLATFORMS=cpu python -m tools.loadgen --smoke

echo "== [6/11] fast tests (with coverage gate)"
COVGATE_MIN="${COVGATE_MIN:-85}" \
    python -m pytest tests/ -q -m "not slow" -x -p tools.covgate

echo "== [7/11] race-analog: seeded-scheduler + threaded-transport suites"
python -m pytest tests/test_transport.py tests/test_byzantine.py \
    tests/test_semantic_byzantine.py tests/test_grpc.py -q -x -m "not slow"

echo "== [8/11] lock sanitizer: @guarded_by runtime assertions armed"
# the same annotation registry staticcheck proves statically, watched
# dynamically: every guarded attribute access must hold its declared
# lock (utils/lockcheck.py); the lock-sensitive suites + one fuzz
# band run armed, so a discipline hole the static rules cannot see
# (dynamic dispatch, callbacks) still gates
CLEISTHENES_LOCKCHECK=1 python -m pytest tests/test_transport.py \
    tests/test_hub.py tests/test_ledger.py tests/test_lockcheck.py \
    -q -x -m "not slow"
LOCKCHECK_FUZZ_OUT="$(mktemp -d /tmp/cleisthenes_fuzz_lc.XXXXXX)"
CLEISTHENES_LOCKCHECK=1 JAX_PLATFORMS=cpu python -m tools.fuzz \
    --seeds 0:20 --out "$LOCKCHECK_FUZZ_OUT"
rm -rf "$LOCKCHECK_FUZZ_OUT"

echo "== [9/11] fault gate: crash/partition/adversary suite, 3-seed matrix"
# the full faults-marked suite already ran at the default seed in
# stages 4-5; the matrix replays the FAULT_SEED-parametrized
# crash+WAL-restart+CATCHUP scenario (the seed-sensitive entry point)
# at every matrix seed, so a fault regression on ANY seed gates
for seed in 11 23 47; do
    echo "   -- FAULT_SEED=$seed"
    FAULT_SEED="$seed" python -m pytest tests/test_byzantine.py -q -x \
        -m faults -k crash_restart_wal_catchup
done

echo "== [10/11] fuzz smoke: semantic+wire schedule fuzzer, 20-seed band"
# 4-node seeded clusters, composite behavior/wire/crash schedules;
# any invariant violation exits non-zero, leaving the shrunken repro
# + trace artifact in FUZZ_OUT (cleaned only on success)
FUZZ_OUT="$(mktemp -d /tmp/cleisthenes_fuzz_ci.XXXXXX)"
JAX_PLATFORMS=cpu python -m tools.fuzz --seeds 0:20 --out "$FUZZ_OUT"
# dynamic-membership band: the same composite schedules run ACROSS a
# join/retire reshare ceremony and its activation boundary — ledger,
# roster-version and key-material agreement must span the roster
# change (the 200-seed deep sweep rides the slow tier,
# tests/test_fuzz.py::test_fuzz_reconfig_deep_sweep)
JAX_PLATFORMS=cpu python -m tools.fuzz --seeds 0:20 --reconfig \
    --rounds 16 --out "$FUZZ_OUT"
# K-deep pipelined-frontier band (ISSUE 15): the same composite
# schedules PINNED to depth 2 and depth 4 — the cross-frontier
# invariants (settled prefix ⊆ ordered log, byte-identical honest
# ordered logs, decrypt-lag bound) must hold over the widened
# in-flight window (the 200-seed deep sweep rides the slow tier,
# tests/test_fuzz.py::test_fuzz_pipeline_deep_sweep)
JAX_PLATFORMS=cpu python -m tools.fuzz --seeds 0:10 \
    --pipeline-depth 2 --out "$FUZZ_OUT"
JAX_PLATFORMS=cpu python -m tools.fuzz --seeds 10:20 \
    --pipeline-depth 4 --out "$FUZZ_OUT"
# WAN emulation band (ISSUE 16): the same composite schedules over a
# seeded link-model plane — per-link latency/jitter/loss/bandwidth,
# heavy-tailed stragglers — with the profile itself drawn from the
# seed; every invariant must hold under geo-realistic delivery
# schedules (the 200-seed deep sweep rides the slow tier,
# tests/test_fuzz.py::test_fuzz_wan_deep_sweep)
JAX_PLATFORMS=cpu python -m tools.fuzz --seeds 0:20 --wan \
    --out "$FUZZ_OUT"
# client-ingress band (ISSUE 18): every tx submits through the
# in-proc twin of the client gRPC surface — encoded client frames ->
# IngressPlane -> fee-priority mempool — with capacity/client-cap/
# dup schedules drawn from the seed (appended LAST, extending the
# historical stream); gates the settle-exactly-once invariant: every
# acked-and-unevicted tx settles exactly once, dedup/backpressure
# acks honor the admission contract, and subscribe(0) replays the
# settled epochs gap-free
JAX_PLATFORMS=cpu python -m tools.fuzz --seeds 0:20 --ingress \
    --out "$FUZZ_OUT"
# attested reduced-quorum band (ISSUE 19): n = 2f+1 rosters under the
# simulated-TEE trust model — attested_log + reduced_quorum armed,
# equivocator-biased adversaries — gating the attestation invariants
# on top of the classic ones: no honest node is ever accused, every
# equivocation the vault refused shows up in the directory's accused
# set, and the honest ledgers stay byte-identical at n - f quorums
# (appended LAST, extending the historical stream)
JAX_PLATFORMS=cpu python -m tools.fuzz --seeds 0:20 \
    --reduced-quorum --out "$FUZZ_OUT"
# lane shard-out band (ISSUE 20): Config.lanes drawn from {2,3,4}
# per seed (appended LAST, extending the historical stream) — S
# parallel HBBFT lanes over one roster with hash-partitioned
# admission and the deterministic cross-lane total-order merge —
# gating merge-determinism (honest merged orders byte-identical),
# cross-lane settle-exactly-once and the per-lane two-frontier
# invariants (the 200-seed deep sweep rides the slow tier,
# tests/test_fuzz.py::test_fuzz_lanes_deep_sweep)
JAX_PLATFORMS=cpu python -m tools.fuzz --seeds 0:20 --lanes \
    --out "$FUZZ_OUT"
rm -rf "$FUZZ_OUT"

if [[ "${CI_FAST:-0}" == "1" ]]; then
    echo "== [11/11] skipped (CI_FAST=1)"
else
    echo "== [11/11] full suite incl. scale tests"
    python -m pytest tests/ -q -m slow
fi

echo "== CI gate PASSED"
