#!/usr/bin/env bash
# CI gate: the one command that gates the tree.
#
# Mirrors the reference's PR pipeline (reference .travis.yml:24-27 +
# travis/run_on_pull_requests.sh: goimports format gate, `go test -v`,
# then `go test -race`), translated to this stack:
#
#   1. format/syntax gate  — compileall over package + tests (no
#      third-party formatter is baked into the image; syntax+bytecode
#      compilation is the deterministic equivalent gate)
#   2. fast test tier      — pytest minus the multi-minute scale tests
#   3. race-analog tier    — the seeded deterministic-scheduler suites
#      (transport/byzantine), this stack's answer to `-race`
#      (SURVEY.md §5.2: replayable interleavings instead of a dynamic
#      race detector), plus the real-thread gRPC suite
#   4. full tier           — everything, including the N=64 slow test
#      (skipped when CI_FAST=1)
#
# Usage:  ./ci.sh          # full gate
#         CI_FAST=1 ./ci.sh  # pre-push quick gate

set -euo pipefail
cd "$(dirname "$0")"

echo "== [1/4] syntax gate: compileall"
python -m compileall -q cleisthenes_tpu tests bench.py __graft_entry__.py

echo "== [2/4] fast tests"
python -m pytest tests/ -q -m "not slow" -x

echo "== [3/4] race-analog: seeded-scheduler + threaded-transport suites"
python -m pytest tests/test_transport.py tests/test_byzantine.py \
    tests/test_grpc.py -q -x

if [[ "${CI_FAST:-0}" == "1" ]]; then
    echo "== [4/4] skipped (CI_FAST=1)"
else
    echo "== [4/4] full suite incl. scale tests"
    python -m pytest tests/ -q -m slow
fi

echo "== CI gate PASSED"
